"""Offline trace/recorder-dump summarizer (docs/observability.md).

Turns an ``Observability.dump_to()`` JSON file (or the crash-dump file
the engine writes on an unhandled exception) into a human-readable
report: per-request latency breakdown (queue wait, prefill time,
decode dispatches, preemptions, end-to-end), the shed/quarantine
tally, the degradation-ladder timeline, recorded incidents, and the
headline metric quantiles. The consumer of a dead bench round's
post-mortem, runnable anywhere (stdlib only — no jax import)::

    python tools/trace_summary.py run_dump.json

Wired into ``bench.py --smoke`` (the ``bench_obs_pipeline`` section)
so the dump -> summarize pipeline is certified end to end on every
smoke run, not first exercised at the incident.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List


def _fmt_s(v) -> str:
    return f"{float(v):.6f}s"


def _request_rows(timelines: Dict[str, List[Dict]]) -> List[Dict]:
    rows = []
    for uid in sorted(timelines):
        evs = timelines[uid]
        if not evs:
            continue
        submit = next((e["t"] for e in evs if e["type"] == "enqueue"),
                      evs[0]["t"])
        terminal = [e for e in evs if e["type"] == "terminal"]
        status = terminal[-1].get("status") if terminal else "in-flight"
        end = terminal[-1]["t"] if terminal else evs[-1]["t"]
        rows.append({
            "uid": uid,
            "status": status,
            "wait_s": sum(e.get("wait_s", 0.0) for e in evs
                          if e["type"] == "admit"),
            "prefill_chunks": sum(e["type"] == "prefill_chunk"
                                  for e in evs),
            "prefill_s": sum(e.get("dur_s", 0.0) for e in evs
                             if e["type"] == "prefill_chunk"),
            "dispatches": sum(e["type"] == "decode" for e in evs),
            "decode_tokens": sum(e.get("tokens", 0) for e in evs
                                 if e["type"] == "drain"),
            "preemptions": sum(e["type"] == "preempt" for e in evs),
            "sheds": [e.get("reason") for e in evs
                      if e["type"] == "shed"],
            "total_s": max(0.0, end - submit),
        })
    return rows


def summarize(dump: Dict) -> str:
    """The report, as one printable string (also the programmatic
    surface bench's smoke section asserts on)."""
    lines: List[str] = ["== apex_tpu observability dump summary =="]
    if dump.get("error"):
        lines.append(f"CRASH DUMP: {dump['error']}")
    trace = dump.get("trace") or {}
    rec = dump.get("recorder") or {}
    lines.append(
        f"trace: {trace.get('num_events', 0)} events "
        f"({trace.get('dropped', 0)} dropped) | recorder: "
        f"{len(rec.get('events', ()))} events "
        f"({rec.get('dropped', 0)} dropped, "
        f"{len(rec.get('incidents', ()))} incidents)")

    rows = _request_rows(trace.get("timelines") or {})
    if rows:
        lines.append(f"-- per-request lifecycle ({len(rows)} requests)")
        for r in rows:
            shed = (f" shed={','.join(map(str, r['sheds']))}"
                    if r["sheds"] else "")
            lines.append(
                f"  {r['uid']}: {r['status']} | wait {_fmt_s(r['wait_s'])}"
                f" | prefill {_fmt_s(r['prefill_s'])}"
                f" ({r['prefill_chunks']} chunks) | {r['dispatches']}"
                f" dispatches -> {r['decode_tokens']} decode tokens | "
                f"{r['preemptions']} preemptions | total "
                f"{_fmt_s(r['total_s'])}{shed}")

    shed_tally: Dict[str, int] = {}
    for evs in (trace.get("timelines") or {}).values():
        for e in evs:
            if e["type"] == "shed":
                reason = str(e.get("reason"))
                shed_tally[reason] = shed_tally.get(reason, 0) + 1
    lines.append("-- shed tally: " + (", ".join(
        f"{k}={v}" for k, v in sorted(shed_tally.items()))
        if shed_tally else "none"))

    rec_events = rec.get("events") or []
    quar = [e for e in rec_events
            if e.get("kind") in ("quarantine", "drafter_quarantine")]
    lines.append(
        "-- quarantines: " + (", ".join(
            f"{e['kind']}({e.get('uid', '-')}) @ {_fmt_s(e['t'])}"
            for e in quar) if quar else "none"))
    ladder = [e for e in rec_events if e.get("kind") == "ladder"]
    lines.append("-- ladder timeline: " + (" ; ".join(
        f"{_fmt_s(e['t'])} {e.get('direction')} -> rung {e.get('level')}"
        for e in ladder) if ladder else "no transitions"))
    resets = [e for e in rec_events if e.get("kind") == "device_reset"]
    if resets:
        lines.append(f"-- device resets: {len(resets)}")
    downs = [e for e in rec_events if e.get("kind") == "replica_down"]
    fails = [e for e in rec_events if e.get("kind") == "failover"]
    migs = [e for e in rec_events if e.get("kind") == "migrate"]
    if downs or fails or migs:
        lines.append(
            f"-- fleet: {len(downs)} replicas down "
            f"({', '.join(str(e.get('reason')) for e in downs)}), "
            f"{len(fails)} failovers re-homing "
            f"{sum(int(e.get('rehomed', 0)) for e in fails)} requests "
            f"(+{sum(int(e.get('adopted', 0)) for e in fails)} results "
            f"adopted from checkpoints), {len(migs)} migrations moving "
            f"{sum(int(e.get('requests', 0)) for e in migs)} requests")
    handoffs = [e for e in rec_events
                if e.get("kind") == "prefill_handoff"]
    if handoffs:
        last = handoffs[-1]
        lines.append(
            f"-- disaggregation: {len(handoffs)} handoff sweeps moving "
            f"{sum(int(e.get('requests', 0)) for e in handoffs)} "
            f"requests prefill->decode "
            f"({sum(int(e.get('bytes', 0)) for e in handoffs)} payload "
            f"bytes); queue depths at last handoff: "
            f"prefill={last.get('prefill_queue', 0)} "
            f"decode={last.get('decode_queue', 0)}")
    spawns = [e for e in rec_events if e.get("kind") == "replica_spawn"]
    retires = [e for e in rec_events
               if e.get("kind") == "replica_retire"]
    rpc_tos = [e for e in rec_events if e.get("kind") == "rpc_timeout"]
    if spawns or retires or rpc_tos:
        grew = ", ".join(f"r{e.get('replica')} @ {_fmt_s(e['t'])}"
                         for e in spawns) or "-"
        shrank = ", ".join(f"r{e.get('replica')} @ {_fmt_s(e['t'])}"
                           for e in retires) or "-"
        lines.append(
            f"-- autoscaler: {len(spawns)} spawns ({grew}), "
            f"{len(retires)} retires ({shrank}), "
            f"{len(rpc_tos)} rpc timeouts")
    spills = [e for e in rec_events if e.get("kind") == "spill"]
    uploads = [e for e in rec_events if e.get("kind") == "spill_upload"]
    if spills or uploads:
        lines.append(
            f"-- spill tier: {len(spills)} blocks spilled "
            f"({sum(int(e.get('bytes', 0)) for e in spills)} bytes), "
            f"{sum(int(e.get('blocks', 0)) for e in uploads)} blocks "
            f"re-admitted by upload across {len(uploads)} admissions")
    dequants = [e for e in rec_events if e.get("kind") == "dequant_gemm"]
    if dequants:
        e = dequants[-1]
        fp_b = int(e.get("fp_bytes", 0))
        q_b = int(e.get("quant_bytes", 0))
        ratio = (fp_b / q_b) if q_b else 0.0
        lines.append(
            f"-- weight quantization: mode={e.get('mode')} "
            f"({fp_b} fp param bytes -> {q_b} quantized, "
            f"{ratio:.2f}x smaller)")
    pubs = [e for e in rec_events if e.get("kind") == "shared_publish"]
    shits = [e for e in rec_events if e.get("kind") == "shared_hit"]
    if pubs or shits:
        lines.append(
            f"-- shared prefix tier: {len(pubs)} publish sweeps storing "
            f"{sum(int(e.get('blocks', 0)) for e in pubs)} blocks "
            f"({sum(int(e.get('bytes', 0)) for e in pubs)} bytes), "
            f"{sum(int(e.get('blocks', 0)) for e in shits)} blocks "
            f"seeded into replicas across {len(shits)} hits")
    tsteps = [e for e in rec_events if e.get("kind") == "train_step"]
    meshed = [e for e in tsteps if e.get("mesh")]
    if meshed:
        shape = "x".join(str(int(d)) for d in meshed[-1]["mesh"])
        span = sum(float(e.get("host_span_s", 0.0)) for e in meshed)
        lines.append(
            f"-- sharded train: {len(meshed)}/{len(tsteps)} steps "
            f"dispatched on the (batch, model)=({shape}) mesh "
            f"({_fmt_s(span)} host span)")
    scrubs = [e for e in rec_events if e.get("kind") == "scrub"]
    corrupts = [e for e in rec_events
                if e.get("kind") == "corruption_detected"]
    suspects = [e for e in rec_events if e.get("kind") == "sdc_suspect"]
    if scrubs or corrupts or suspects:
        sites: Dict[str, int] = {}
        for e in corrupts:
            s = str(e.get("site"))
            sites[s] = sites.get(s, 0) + 1
        by_site = (" (" + ", ".join(f"{k}={v}" for k, v in
                                    sorted(sites.items())) + ")"
                   if sites else "")
        retired = (" (" + ", ".join(f"replica {e.get('replica')}"
                                    for e in suspects) + ")"
                   if suspects else "")
        lines.append(
            f"-- integrity: {len(scrubs)} scrubs verifying "
            f"{sum(int(e.get('verified', 0)) for e in scrubs)} blocks, "
            f"{len(corrupts)} corruptions caught{by_site}, "
            f"{len(suspects)} SDC suspects retired{retired}")
    incidents = rec.get("incidents") or []
    for inc in incidents:
        lines.append(
            f"-- incident {inc.get('label')!r} @ {_fmt_s(inc.get('t', 0))}"
            f" ({len(inc.get('events', ()))} events frozen)")

    values = (dump.get("metrics") or {}).get("values") or {}
    if values:
        parts = []
        for name in ("serving_ttft_s", "serving_itl_s",
                     "serving_queue_wait_s", "train_step_s"):
            h = values.get(name)
            if isinstance(h, dict) and h.get("count"):
                parts.append(f"{name} p50={h['p50']:.6f} "
                             f"p99={h['p99']:.6f} (n={h['count']})")
        for name in ("serving_requests_total", "serving_tokens_total",
                     "serving_sheds_total", "serving_preemptions_total",
                     "train_steps_total"):
            if name in values:
                parts.append(f"{name}={values[name]:g}")
        if parts:
            lines.append("-- metrics: " + " | ".join(parts))
    return "\n".join(lines)


def summarize_file(path: str) -> str:
    with open(path, encoding="utf-8") as f:
        return summarize(json.load(f))


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python tools/trace_summary.py <dump.json>",
              file=sys.stderr)
        return 2
    print(summarize_file(argv[0]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
