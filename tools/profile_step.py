"""Component-level on-chip profile of the BERT-large headline step.

Chained-carry timing (the only trustworthy pattern on the axon tunnel —
see .claude/skills/verify/SKILL.md): state evolves through every call,
block once per window, best-of-3 windows, salted inputs. Each component
is timed fwd+bwd in isolation so the 210-ish ms step decomposes into an
actionable budget (attention kernels / encoder matmuls / MLM tail /
optimizer) against the 141 TFLOP/s measured matmul ceiling.

Usage:  python tools/profile_step.py [component ...]
        components: attn encoder tail matmul embed opt step
                    dequant_gemm train_sharded
        (default: all; `opt` needs a ~10-minute standalone compile)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

_SALT = int(time.time() * 1e3) % (2 ** 30)

B, S, H, NH, D, L, I = 16, 512, 1024, 16, 64, 24, 4096
V = 30522
PEAK = 197e12


def _chain(step, state, iters=8, warmup=2, windows=2):
    """Delegates to bench.marginal_time — ONE timing methodology for
    the whole repo (value-fetch barrier + positive-marginal guard)."""
    import bench

    for _ in range(warmup):
        state = step(*state)
    bench._fetch(state)
    box = [state]

    def advance(n):
        for _ in range(n):
            box[0] = step(*box[0])

    return bench.marginal_time(advance, lambda: bench._fetch(box[0]),
                               iters, windows=windows)


def _reset():
    import gc

    gc.collect()
    jax.clear_caches()
    gc.collect()


def prof_attention():
    """24 layers of flash attention (B, NH, S, D) fwd+bwd, dropout 0.1."""
    from apex_tpu.ops.flash_attention import flash_attention

    # fp32 carry: a bf16 carry with a tiny update rounds back to the
    # IDENTICAL input and the runtime memoizer serves the whole step
    # from cache (observed: 0.02 ms "measurement")
    q = jax.random.normal(jax.random.PRNGKey(_SALT), (B, NH, S, D),
                          jnp.float32)

    def loss(qc):
        x = qc.astype(jnp.bfloat16)
        for i in range(L):
            x = flash_attention(x, x, x, None, False, 0.125, 0.1,
                                _SALT + i)
        return jnp.sum(x.astype(jnp.float32) ** 2)

    @jax.jit
    def step(q):
        dq = jax.grad(loss)(q)
        return (0.999 * q - 1e-3 * jnp.tanh(dq),)

    dt = _chain(step, (q,))
    # useful flops: 4*B*S^2*H per layer fwd, 3 matmuls of same size in
    # bwd (recompute s + dq/dk/dv/dp makes it 5+2 kernel matmuls, but
    # the MFU convention counts fwd 2 + bwd 4 matmul-equivalents)
    flops = 12.0 * L * B * S * S * H
    print(f"attention x{L} fwd+bwd (dropout .1): {dt*1e3:7.2f} ms  "
          f"({flops/dt/1e12:5.1f} TFLOP/s conv, {flops/dt/PEAK:.3f} MFU; "
          f"kernel does 7/6 of counted matmuls)")
    return dt


def prof_encoder():
    """Encoder-only (BertModel, no heads/loss/optimizer) fwd+bwd at the
    true dropout config."""
    from apex_tpu.models import BertConfig, BertModel

    cfg = BertConfig.bert_large(dtype=jnp.bfloat16, remat=False)
    model = BertModel(cfg)
    rng = np.random.RandomState(_SALT)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)))
    types = jnp.zeros((B, S), jnp.int32)
    mask = jnp.ones((B, S), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids, types, mask)["params"]

    def loss(p, key):
        x, pooled = model.apply({"params": p}, ids, types, mask,
                                deterministic=False,
                                rngs={"dropout": key})
        return jnp.sum(x.astype(jnp.float32) ** 2) * 1e-6

    @jax.jit
    def step(p, key):
        key, sub = jax.random.split(key)
        g = jax.grad(loss)(p, sub)
        # bounded but bf16/f32-visible update: keeps inputs fresh for
        # the memoizer without blowing up over the timing loop
        p2 = jax.tree.map(
            lambda a, b: 0.9995 * a - 1e-4 * jnp.tanh(b.astype(jnp.float32)
                                                      ).astype(a.dtype),
            p, g)
        return p2, key

    dt = _chain(step, (params, jax.random.PRNGKey(_SALT)))
    enc_params = sum(x.size for x in jax.tree.leaves(params))
    flops = 6.0 * enc_params * B * S + 12.0 * L * B * S * S * H
    print(f"encoder-only fwd+bwd (dropout .1):  {dt*1e3:7.2f} ms  "
          f"({flops/dt/1e12:5.1f} TFLOP/s, {flops/dt/PEAK:.3f} MFU, "
          f"{enc_params/1e6:.0f}M params)")
    return dt


def prof_tail():
    """MLM head + loss tail alone: transform -> gelu -> LN -> decoder ->
    logsumexp loss (+ NSP head), fwd+bwd from a (B, S, H) activation."""
    from apex_tpu.models.bert import pretraining_loss
    from apex_tpu.normalization import FusedLayerNorm
    import flax.linen as nn

    rng = np.random.RandomState(_SALT)
    x = jnp.asarray(rng.randn(B, S, H).astype("f4") * 0.1)  # f32 carry
    labels = jnp.asarray(
        np.where(rng.rand(B, S) < 0.15, rng.randint(0, V, (B, S)), -1))
    nsp = jnp.asarray(rng.randint(0, 2, (B,)))

    class Tail(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = nn.Dense(H, dtype=jnp.bfloat16, param_dtype=jnp.float32,
                         name="mlm_transform")(x)
            h = nn.gelu(h)
            h = FusedLayerNorm(H, name="mlm_ln")(h)
            mlm = nn.Dense(V, dtype=jnp.bfloat16, param_dtype=jnp.float32,
                           name="mlm_decoder")(h)
            nspl = nn.Dense(2, dtype=jnp.bfloat16, param_dtype=jnp.float32,
                            name="nsp")(x[:, 0])
            return mlm, nspl

    tail = Tail()
    params = tail.init(jax.random.PRNGKey(0), x)["params"]

    def loss(p, x):
        mlm, nspl = tail.apply({"params": p}, x.astype(jnp.bfloat16))
        return pretraining_loss(mlm, nspl, labels, nsp)

    @jax.jit
    def step(p, x):
        l, (g, gx) = jax.value_and_grad(loss, argnums=(0, 1))(p, x)
        p2 = jax.tree.map(
            lambda a, b: 0.9995 * a - 1e-4 * jnp.tanh(b.astype(jnp.float32)
                                                      ).astype(a.dtype),
            p, g)
        return p2, 0.999 * x - 1e-3 * jnp.tanh(gx)

    dt = _chain(step, (params, x))
    flops = 6.0 * (H * V + H * H) * B * S
    print(f"MLM tail fwd+bwd:                   {dt*1e3:7.2f} ms  "
          f"(matmul-ideal {flops/PEAK*1e3:.1f} ms)")
    return dt


def prof_matmul():
    """Matmul-chain ceiling at the encoder shape."""
    a = jax.random.normal(jax.random.PRNGKey(_SALT), (B * S, H),
                          jnp.bfloat16)
    w1 = jax.random.normal(jax.random.PRNGKey(1), (H, I), jnp.bfloat16)
    w2 = jax.random.normal(jax.random.PRNGKey(2), (I, H), jnp.bfloat16)

    @jax.jit
    def step(a):
        # all-bf16 chain (no fp32 intermediate stores). Normalize by RMS
        # instead of a fixed 0.01 scale: the fixed scale decays the carry
        # to exact zeros in a few steps, after which every call has
        # IDENTICAL inputs and the runtime memoizer serves it instantly
        # (observed: negative marginal times).
        for _ in range(8):
            a = jax.lax.dot(jax.lax.dot(a, w1), w2)
            a = (a * jax.lax.rsqrt(jnp.mean(a.astype(jnp.float32) ** 2)
                                   + 1e-6).astype(a.dtype))
        return (a,)

    dt = _chain(step, (a,), iters=8)
    flops = 8 * 2 * 2.0 * B * S * H * I
    print(f"matmul chain ceiling:               {dt*1e3:7.2f} ms  "
          f"({flops/dt/1e12:5.1f} TFLOP/s = {flops/dt/PEAK:.2f} of peak)")
    return dt


def prof_dequant_gemm():
    """Quantized-weight matmul chain at the encoder shape: the XLA
    dequant-then-matmul reference vs the fused Pallas dequant-GEMM
    (apex_tpu.ops.dequant_gemm) vs the fp matmul floor — the decode
    weight-read path docs/serving.md's weight_quantization knob buys.
    Same RMS-normalized carry as prof_matmul (defeats the runtime
    memoizer)."""
    from apex_tpu.models.gpt import quantize_dense_kernel
    from apex_tpu.ops import dequant_gemm as dg

    a = jax.random.normal(jax.random.PRNGKey(_SALT), (B * S, H),
                          jnp.float32)
    w1 = jax.random.normal(jax.random.PRNGKey(1), (H, I), jnp.float32)
    w2 = jax.random.normal(jax.random.PRNGKey(2), (I, H), jnp.float32)
    q1, s1 = quantize_dense_kernel(w1, "int8")
    q2, s2 = quantize_dense_kernel(w2, "int8")
    flops = 8 * 2 * 2.0 * B * S * H * I
    results = {}

    def norm(a):
        return a * jax.lax.rsqrt(
            jnp.mean(a.astype(jnp.float32) ** 2) + 1e-6).astype(a.dtype)

    for label, mm in (
            ("fp32 matmul floor", lambda x, w, q, s: jnp.dot(x, w)),
            ("XLA dequant chain",
             lambda x, w, q, s: dg.dequant_matmul_reference(x, q, s)),
            ("fused dequant-GEMM",
             lambda x, w, q, s: dg.dequant_matmul(x, q, s,
                                                  use_pallas=True))):

        @jax.jit
        def step(a, mm=mm):
            for _ in range(8):
                a = norm(mm(mm(a, w1, q1, s1), w2, q2, s2))
            return (a,)

        dt = _chain(step, (a,), iters=8)
        results[label] = dt
        print(f"dequant_gemm {label:<22s} {dt*1e3:7.2f} ms  "
              f"({flops/dt/1e12:5.1f} TFLOP/s)")
    return results


def prof_step():
    """Full headline step via bench._measure (same session)."""
    sys.path.insert(0, "/root/repo")
    import bench

    dt, _, mfu = bench._measure(B, S, iters=8, with_baseline=False,
                                remat=False)
    return dt


def prof_embed():
    """BertEmbeddings fwd+bwd alone: vocab gather + pos/type add + LN +
    dropout forward; the backward's cost center is the scatter-add of
    (B*S, H) token grads into the (30522, H) embedding table."""
    from apex_tpu.models import BertConfig
    from apex_tpu.models.bert import BertEmbeddings

    cfg = BertConfig.bert_large(dtype=jnp.bfloat16)
    emb = BertEmbeddings(cfg)
    rng = np.random.RandomState(_SALT)
    ids = jnp.asarray(rng.randint(0, V, (B, S)))
    types = jnp.zeros((B, S), jnp.int32)
    params = emb.init(jax.random.PRNGKey(0), ids, types)["params"]

    def loss(p, key):
        x = emb.apply({"params": p}, ids, types, deterministic=False,
                      rngs={"dropout": key})
        return jnp.sum(x.astype(jnp.float32) ** 2) * 1e-6

    @jax.jit
    def step(p, key):
        key, sub = jax.random.split(key)
        g = jax.grad(loss)(p, sub)
        p2 = jax.tree.map(
            lambda a, b: 0.9995 * a - 1e-4 * jnp.tanh(b.astype(jnp.float32)
                                                      ).astype(a.dtype),
            p, g)
        return p2, key

    dt = _chain(step, (params, jax.random.PRNGKey(_SALT)))
    print(f"embeddings fwd+bwd:                 {dt*1e3:7.2f} ms")
    return dt


def prof_opt(fraction=1.0):
    """Full-size FusedLAMB O2 step alone (367M params, fp32 masters +
    both moments): state traffic is ~11 GB/step, so the bandwidth
    roofline is ~13 ms — this measures how close the fused update runs
    to it. NOTE: the 399-leaf compile regularly exceeds 10 minutes
    through the tunnel and sometimes drops it (retry loop); round 5 the
    tunnel started rejecting the full program outright (HTTP 413
    request-body limit), so on 413 the profile falls back to a leaf
    SUBSET and scales the measured time by the state-bytes ratio — the
    update is bandwidth-bound, so time scales with bytes (the scaled
    number is labeled as an estimate)."""
    import apex_tpu.amp as amp
    from apex_tpu.models import BertConfig, BertForPreTraining
    from apex_tpu.optimizers import FusedLAMB

    cfg = BertConfig.bert_large(dtype=jnp.bfloat16)
    model = BertForPreTraining(cfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids, None,
                        jnp.ones((1, 8), jnp.int32))["params"]
    full_bytes = sum(p.size * p.dtype.itemsize
                     for p in jax.tree.leaves(params))
    if fraction < 1.0:
        # keep every k-th leaf (size-ordered round-robin keeps the
        # big/small mix representative of the real tree)
        flat = jax.tree.leaves(params)
        order = sorted(range(len(flat)), key=lambda i: -flat[i].size)
        stride = max(int(round(1.0 / fraction)), 1)
        keep = {i for pos, i in enumerate(order) if pos % stride == 0}
        params = {f"leaf{i}": flat[i] for i in sorted(keep)}
    sub_bytes = sum(p.size * p.dtype.itemsize
                    for p in jax.tree.leaves(params))
    opt = FusedLAMB(lr=1e-4, weight_decay=0.01)
    params, opt, handle = amp.initialize(params, opt, opt_level="O2",
                                         verbosity=0)
    ost = opt.init(params)
    grads = jax.tree.map(lambda p: (p * 1e-3).astype(p.dtype), params)

    @jax.jit
    def step(params, ost, c):
        p2, ost2, found = opt.step(
            jax.tree.map(lambda g: g * (1.0 + c * 1e-6), grads), ost,
            params, grad_scale=jnp.float32(65536.0))
        return p2, ost2, c + 1.0

    for attempt in range(3):
        try:
            # _chain does warmup + fetch before timing, so the huge
            # compile lands outside every timed window
            dt = _chain(step,
                        (params, ost, jnp.float32(_SALT % 1000 + attempt)))
            if fraction >= 1.0:
                print(f"optimizer (FusedLAMB O2 367M):      {dt*1e3:7.2f} ms"
                      f"  (state-traffic roofline ~13 ms)")
                return dt
            est = dt * full_bytes / sub_bytes
            print(f"optimizer (FusedLAMB O2, {sub_bytes/full_bytes:.0%} "
                  f"leaf subset): {dt*1e3:7.2f} ms -> full-tree "
                  f"ESTIMATE {est*1e3:7.2f} ms (bytes-scaled)")
            return est  # keep the component-budget return contract
        except Exception as e:
            # "HTTP 413" is the tunnel's request-body-limit rejection
            # verbatim (substring-matching bare "413" would trip on
            # tensor dims/byte counts inside unrelated errors)
            if "HTTP 413" in repr(e) and fraction > 0.1:
                print(f"# prof_opt: program rejected by the tunnel "
                      f"(HTTP 413) at fraction={fraction}; halving "
                      f"the leaf subset", file=sys.stderr)
                return prof_opt(fraction=fraction / 2.0)
            if attempt == 2:    # transient tunnel drops are retried;
                raise           # anything else must surface
            print(f"# prof_opt attempt {attempt}: {e!r}", file=sys.stderr)
    return None


def prof_train_sharded():
    """GPT-tiny 3D-parallel fused train step (docs/training.md
    "Sharded training") on the largest (batch, model) mesh this host's
    devices allow, chained-carry timed like every other component;
    also prints the AOT-audited per-step collective totals so the
    wall-clock attributes to the ZeRO/TP legs, not to guesswork."""
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.models.gpt import GPTConfig, GPTLMHeadModel, lm_loss
    from apex_tpu.serving.mesh import build_mesh
    from apex_tpu.train import build_train_step

    n = jax.device_count()
    shape = (2, 2) if n >= 4 else ((1, 2) if n >= 2 else (1, 1))
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    rng = np.random.RandomState(_SALT)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 4, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens[0])["params"]

    def loss_fn(p, mb):
        return lm_loss(model.apply({"params": p}, mb), mb)

    ts = build_train_step(
        loss_fn, DistributedFusedAdam(lr=1e-3, flat_mode="global"),
        accum_steps=2, mesh=build_mesh(shape), num_heads=cfg.num_heads)
    state = ts.init(params)
    audit = ts.audit_collectives(state, tokens)
    total = audit["collectives"]["total"]["ops"]

    def step(st):
        st2, _ = ts.step(st, tokens)
        return (st2,)

    dt = _chain(step, (state,))
    print(f"train-sharded GPT-tiny @ mesh{shape}: {dt*1e3:7.2f} ms/step "
          f"({1.0/dt:5.2f} steps/s; {total} collectives/step, donation "
          f"aliases {audit['alias']['pairs']} covering "
          f"{audit['sharded_leaves']} sharded leaves)")
    return dt


COMPONENTS = {"attn": prof_attention, "encoder": prof_encoder,
              "tail": prof_tail, "matmul": prof_matmul,
              "embed": prof_embed, "opt": prof_opt, "step": prof_step,
              "dequant_gemm": prof_dequant_gemm,
              "train_sharded": prof_train_sharded}


def main():
    want = [a for a in sys.argv[1:] if a in COMPONENTS] or list(COMPONENTS)
    for name in want:
        _reset()
        COMPONENTS[name]()
        _reset()


if __name__ == "__main__":
    main()
