"""Offline comparer of two bench artifacts (``BENCH_*.json``).

The bench record has carried per-section ``{"section", "status",
"wall_time_s"}`` exit records since PR 6 (the BENCH_r01/r05 lesson: a
dead section must be a visible "failed" entry, not an absence) — but
nothing CONSUMED them: a round whose section quietly vanished from the
artifact still read as a clean round to a human eyeballing the metric
lines. This tool closes that loop, stdlib-only so it runs anywhere the
artifacts land::

    python tools/bench_diff.py BENCH_r04.json BENCH_r05.json

For each section: status transition (``ok -> failed`` and a section
PRESENT in the old artifact but MISSING from the new one both fail the
diff, rc != 0 — a disappeared section is the r01/r05 failure mode
itself). For each metric: value/ratio delta and the ``vs_baseline``
movement. New sections/metrics are reported as additions, never
failures.

Accepted inputs, per file: the driver's wrapper JSON (``{"rc", "tail",
"parsed", ...}`` — records are parsed out of the ``tail`` text), or a
raw text/JSON-lines file of bench stdout. Unparseable lines (tail
truncation) are skipped.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, Tuple


def parse_artifact(path: str) -> Dict[str, Dict]:
    """``{"metrics": {name: record}, "sections": {name: record},
    "rc": int | None}`` from one artifact file."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    rc = None
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "tail" in doc:
        rc = doc.get("rc")
        lines = str(doc.get("tail") or "").splitlines()
        if isinstance(doc.get("parsed"), dict):
            lines.append(json.dumps(doc["parsed"]))
    elif isinstance(doc, list):
        lines = [json.dumps(r) for r in doc]
    else:
        lines = text.splitlines()
    metrics: Dict[str, Dict] = {}
    sections: Dict[str, Dict] = {}
    for line in lines:
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue    # truncated tail / non-record JSON-ish noise
        if not isinstance(rec, dict):
            continue
        if "metric" in rec:
            metrics[str(rec["metric"])] = rec
        elif "section" in rec:
            sections[str(rec["section"])] = rec
    return {"metrics": metrics, "sections": sections, "rc": rc}


def _fmt_delta(old, new) -> str:
    try:
        o, n = float(old), float(new)
    except (TypeError, ValueError):
        return f"{old!r} -> {new!r}"
    ratio = (n / o) if o else float("inf")
    return f"{o:g} -> {n:g} ({ratio:.3f}x)"


def diff(old: Dict[str, Dict], new: Dict[str, Dict]
         ) -> Tuple[int, list]:
    """Compare two parsed artifacts. Returns ``(rc, lines)`` — rc 1
    when a section disappeared or regressed ok -> failed."""
    lines = []
    rc = 0
    lines.append(f"rc: {old['rc']} -> {new['rc']}")
    o_sec, n_sec = old["sections"], new["sections"]
    for name in sorted(set(o_sec) | set(n_sec)):
        if name not in n_sec:
            lines.append(f"SECTION DISAPPEARED: {name} (was "
                         f"{o_sec[name].get('status')!r}) — the "
                         f"r01/r05 failure mode")
            rc = 1
            continue
        if name not in o_sec:
            lines.append(f"section added: {name} "
                         f"({n_sec[name].get('status')!r})")
            continue
        so = o_sec[name].get("status")
        sn = n_sec[name].get("status")
        if so == sn:
            lines.append(f"section {name}: {sn!r} (unchanged, "
                         f"{_fmt_delta(o_sec[name].get('wall_time_s'), n_sec[name].get('wall_time_s'))} wall)")
        else:
            lines.append(f"SECTION STATUS: {name}: {so!r} -> {sn!r}")
            if sn != "ok":
                rc = 1
    o_met, n_met = old["metrics"], new["metrics"]
    for name in sorted(set(o_met) | set(n_met)):
        if name not in n_met:
            # a metric can legitimately move between rounds (renames,
            # TPU-only rows on a CPU round) — report, don't fail; the
            # SECTION records above are the liveness contract
            lines.append(f"metric gone: {name} "
                         f"(was {o_met[name].get('value')})")
            continue
        if name not in o_met:
            lines.append(f"metric added: {name} = "
                         f"{n_met[name].get('value')}")
            continue
        o, n = o_met[name], n_met[name]
        lines.append(
            f"metric {name}: {_fmt_delta(o.get('value'), n.get('value'))}"
            f" [{n.get('unit', '?')}], vs_baseline "
            f"{_fmt_delta(o.get('vs_baseline'), n.get('vs_baseline'))}")
    if not (o_sec or n_sec):
        lines.append("note: neither artifact carries section records "
                     "(pre-PR-6 rounds) — liveness not checkable")
    return rc, lines


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 2:
        print("usage: python tools/bench_diff.py <OLD.json> <NEW.json>",
              file=sys.stderr)
        return 2
    rc, lines = diff(parse_artifact(argv[0]), parse_artifact(argv[1]))
    print(f"== bench diff: {argv[0]} -> {argv[1]} ==")
    for line in lines:
        print(line)
    print(f"== verdict: {'FAIL' if rc else 'ok'} ==")
    return rc


if __name__ == "__main__":
    sys.exit(main())
