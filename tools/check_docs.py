"""Doc-drift lint: the serving surface must stay documented.

Asserts that every :class:`~apex_tpu.serving.EngineConfig` field, every
:class:`~apex_tpu.serving.TenantQuota` field, and every top-level
``stats()`` counter key of a live engine is NAMED somewhere in
``docs/serving.md`` or ``docs/robustness.md`` — so the next knob or
counter cannot land undocumented. Wired in as a tier-1 test
(tests/test_docs_lint.py); also runnable standalone::

    JAX_PLATFORMS=cpu python tools/check_docs.py   # exit 1 on drift

The check is by literal name occurrence (the docs must at least SAY
the name); it is a drift tripwire, not a prose-quality judge.
"""

from __future__ import annotations

import dataclasses
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC_FILES = ("docs/serving.md", "docs/robustness.md")


def _docs_text() -> str:
    parts = []
    for rel in DOC_FILES:
        with open(os.path.join(REPO_ROOT, rel), encoding="utf-8") as f:
            parts.append(f.read())
    return "\n".join(parts)


def collect_names():
    """(kind, name) pairs the docs must mention. Building the stats
    surface needs a live engine: a tiny CPU model, never dispatched —
    ``stats()`` is readable from construction."""
    sys.path.insert(0, REPO_ROOT)
    import jax
    import jax.numpy as jnp

    from apex_tpu.models import GPTConfig, GPTLMHeadModel
    from apex_tpu.serving import (EngineConfig, InferenceEngine,
                                  TenantQuota)

    names = [("EngineConfig field", f.name)
             for f in dataclasses.fields(EngineConfig)]
    names += [("TenantQuota field", f.name)
              for f in dataclasses.fields(TenantQuota)]
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    engine = InferenceEngine(model, params, EngineConfig(
        max_batch=2, block_size=4, num_blocks=16, max_prefill_len=8,
        max_seq_len=16))
    names += [("stats() key", k) for k in engine.stats()]
    return names


def main():
    text = _docs_text()
    missing = [(kind, name) for kind, name in collect_names()
               if name not in text]
    for kind, name in missing:
        print(f"UNDOCUMENTED {kind}: {name!r} appears in neither "
              f"{' nor '.join(DOC_FILES)}", file=sys.stderr)
    return missing


if __name__ == "__main__":
    sys.exit(1 if main() else 0)
