"""Doc-drift lint: the serving + observability surfaces must stay
documented.

Asserts that every :class:`~apex_tpu.serving.EngineConfig` field, every
:class:`~apex_tpu.serving.TenantQuota` field, and every top-level
``stats()`` counter key of a live engine is NAMED somewhere in
``docs/serving.md`` or ``docs/robustness.md`` — that every trace
event type, flight-recorder event kind, and exported metric name of
the observability layer is named in ``docs/observability.md`` — and
that every :class:`~apex_tpu.serving.FleetConfig` field and top-level
fleet ``stats()`` key is named in ``docs/fleet.md`` — so the next
knob, counter, event, or metric cannot land undocumented. Wired
in as a tier-1 test (tests/test_docs_lint.py, including a phantom-name
self-test per surface); also runnable standalone::

    JAX_PLATFORMS=cpu python tools/check_docs.py   # exit 1 on drift

The check is by literal name occurrence (the docs must at least SAY
the name); it is a drift tripwire, not a prose-quality judge.
"""

from __future__ import annotations

import dataclasses
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SERVING_DOCS = ("docs/serving.md", "docs/robustness.md")
OBS_DOCS = ("docs/observability.md",)
FLEET_DOCS = ("docs/fleet.md",)
ROBUSTNESS_DOCS = ("docs/robustness.md",)
# kinds whose names belong in docs/observability.md / docs/fleet.md /
# docs/robustness.md specifically; everything else is the serving
# surface
OBS_KINDS = ("trace event type", "recorder event kind", "metric")
FLEET_KINDS = ("FleetConfig field", "fleet stats() key")
INTEGRITY_KINDS = ("integrity surface",)
MESH_KINDS = ("mesh surface",)
WEIGHT_QUANT_KINDS = ("weight quant surface",)
PROCESS_KINDS = ("process surface",)
AUTOSCALE_KINDS = ("autoscale surface",)
DISAGG_KINDS = ("disagg surface",)
SHARED_TIER_KINDS = ("shared tier surface",)
MESH_DOCS = ("docs/serving.md",)
# the pod-scale mesh surface (knob + stats keys) must be named in the
# "Mesh sharding" doc itself, docs/serving.md — same discipline as the
# integrity surface: each name is cross-checked against the live
# config/stats surfaces, so a renamed knob breaks the lint instead of
# silently unpinning it.
MESH_NAMES = (
    "mesh_shape",
    "mesh_devices", "mesh_model_axis", "mesh_batch_axis",
)
# the quantized-storage surface (both mode knobs, their stats() keys,
# and the weight-quantization boot recorder kind) must be named in the
# quantization coverage of docs/serving.md specifically — each name
# cross-checked against the live config/stats/recorder surfaces so a
# rename breaks the lint instead of silently unpinning it.
WEIGHT_QUANT_NAMES = (
    "kv_quantization", "weight_quantization", "dequant_gemm",
)
# the disaggregated prefill/decode surface (role knob, handoff
# counters, the two-stage router's probe-skip tally, and the handoff
# recorder kind) must be named in the "Disaggregated roles" doc,
# docs/fleet.md — each name cross-checked against the live
# FleetConfig/stats/recorder surfaces so a rename breaks the lint.
DISAGG_NAMES = (
    "replica_roles",
    "num_handoffs", "num_handoff_requests", "num_handoff_bytes",
    "num_affinity_probes_skipped",
    "prefill_handoff",
)
# the fleet-global shared prefix tier (budget + scrub-coverage knobs,
# the publish/dedupe/hit/scrub counters, and the two recorder kinds)
# must be named in the "Shared prefix tier" doc, docs/fleet.md — each
# name cross-checked against the live FleetConfig/stats/recorder
# surfaces so a rename breaks the lint.
SHARED_TIER_NAMES = (
    "shared_prefix_bytes", "shared_scrub_blocks",
    "shared_tier_blocks", "shared_tier_bytes", "shared_tier_hits",
    "num_shared_publishes", "num_shared_dedupe",
    "num_shared_evictions", "num_shared_refused",
    "num_shared_corrupt_discards", "num_shared_scrub_blocks_verified",
    "num_hash_walks",
    "shared_publish", "shared_hit",
)
# the process-replica surface (mode knob, RPC policy knobs, and the
# wire-health counters) must be named in the "Process replicas" doc,
# docs/fleet.md — each name cross-checked against the live
# FleetConfig/stats surfaces so a rename breaks the lint.
PROCESS_NAMES = (
    "replica_mode", "rpc_timeout_s", "rpc_retries",
    "num_rpc_retries", "num_rpc_timeouts",
)
# the autoscaler surface (watermarks + hysteresis knobs + the spawn/
# retire tallies) — same discipline, also routed to docs/fleet.md.
AUTOSCALE_NAMES = (
    "autoscale_high_watermark", "autoscale_low_watermark",
    "autoscale_patience", "autoscale_min_replicas",
    "autoscale_max_replicas",
    "num_spawned", "num_retired",
)
# the data-integrity surface (knobs + counters) must be named in the
# "Data integrity" doc itself, docs/robustness.md — not merely
# somewhere in the combined serving text. Each name listed here is
# additionally cross-checked against the live config/stats surfaces,
# so a renamed knob breaks the lint instead of silently unpinning it.
# the sharded-train surface (round 20): the GSPMD knobs on
# build_train_step, the ZeRO flat-buffer knobs + stats() accounting
# keys of DistributedFusedAdam, and the TrainStep audit surface must
# be named in the "Sharded training" doc, docs/training.md — each
# name cross-checked against the live signature/field/stats surfaces
# so a renamed knob breaks the lint instead of silently unpinning it.
TRAIN_DOCS = ("docs/training.md",)
TRAIN_SHARDED_KINDS = ("train sharded surface",)
TRAIN_SHARDED_NAMES = (
    "mesh", "batch_spec", "param_pspec", "num_heads",
    "flat_mode", "group_size",
    "flat_pad_elems", "flat_shard_elems", "flat_world",
    "opt_state_bytes_per_shard",
    "audit_collectives", "mesh_shape",
)
INTEGRITY_NAMES = (
    "verify_artifacts", "scrub_interval_ticks", "scrub_spill_blocks",
    "sdc_check_interval_ticks",
    "num_corruptions_detected", "num_import_refusals", "num_scrubs",
    "num_scrub_blocks_verified", "num_spill_refused",
    "num_spill_corrupt_discards",
    "num_corrupt_checkpoints", "num_refused_imports",
    "num_sdc_checks", "num_sdc_suspects",
)


def _docs_text(files) -> str:
    parts = []
    for rel in files:
        with open(os.path.join(REPO_ROOT, rel), encoding="utf-8") as f:
            parts.append(f.read())
    return "\n".join(parts)


def collect_names():
    """(kind, name) pairs the docs must mention. Building the stats
    surface needs a live engine: a tiny CPU model, never dispatched —
    ``stats()`` is readable from construction. The observability
    names come from the layer's own closed vocabularies (the trace/
    recorder modules reject kinds outside them, so the lint and the
    runtime can't drift apart) and a registry with both metric sets
    registered."""
    sys.path.insert(0, REPO_ROOT)
    import jax
    import jax.numpy as jnp

    from apex_tpu.models import GPTConfig, GPTLMHeadModel
    from apex_tpu.observability import (
        RECORDER_EVENT_KINDS,
        TRACE_EVENT_TYPES,
        MetricsRegistry,
        register_engine_metrics,
        register_train_metrics,
    )
    from apex_tpu.serving import (EngineConfig, FleetConfig, FleetRouter,
                                  InferenceEngine, TenantQuota)

    names = [("EngineConfig field", f.name)
             for f in dataclasses.fields(EngineConfig)]
    names += [("TenantQuota field", f.name)
              for f in dataclasses.fields(TenantQuota)]
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    engine_cfg = EngineConfig(
        max_batch=2, block_size=4, num_blocks=16, max_prefill_len=8,
        max_seq_len=16)
    engine = InferenceEngine(model, params, engine_cfg)
    names += [("stats() key", k) for k in engine.stats()]
    # the fleet surface (docs/fleet.md): router knobs + its stats keys
    # — a live 1-replica router, never stepped (stats() is readable
    # from construction, like the engine's)
    names += [("FleetConfig field", f.name)
              for f in dataclasses.fields(FleetConfig)]
    fleet = FleetRouter(model, params, engine_cfg,
                        FleetConfig(num_replicas=1))
    names += [("fleet stats() key", k) for k in fleet.stats()]
    names += [("trace event type", t) for t in TRACE_EVENT_TYPES]
    names += [("recorder event kind", k) for k in RECORDER_EVENT_KINDS]
    registry = MetricsRegistry()
    register_engine_metrics(registry)
    register_train_metrics(registry)
    names += [("metric", n) for n in registry.names()]
    # the integrity surface: every INTEGRITY_NAMES entry must (a)
    # exist on a live surface collected above — the list cannot name
    # phantoms — and (b) be named in docs/robustness.md specifically
    live = {n for _, n in names}
    for n in INTEGRITY_NAMES:
        if n not in live:
            raise AssertionError(
                f"INTEGRITY_NAMES lists {n!r}, which is no longer a "
                "live EngineConfig/FleetConfig field or stats() key — "
                "update tools/check_docs.py")
        names.append(("integrity surface", n))
    # the mesh surface: same liveness discipline, routed to the
    # "Mesh sharding" doc (docs/serving.md) specifically
    for n in MESH_NAMES:
        if n not in live:
            raise AssertionError(
                f"MESH_NAMES lists {n!r}, which is no longer a live "
                "EngineConfig field or stats() key — update "
                "tools/check_docs.py")
        names.append(("mesh surface", n))
    for n in WEIGHT_QUANT_NAMES:
        if n not in live:
            raise AssertionError(
                f"WEIGHT_QUANT_NAMES lists {n!r}, which is no longer "
                "a live EngineConfig field, stats() key, or recorder "
                "event kind — update tools/check_docs.py")
        names.append(("weight quant surface", n))
    # the process-replica + autoscaler surfaces: liveness-checked like
    # the integrity surface, routed to docs/fleet.md specifically
    for n in PROCESS_NAMES:
        if n not in live:
            raise AssertionError(
                f"PROCESS_NAMES lists {n!r}, which is no longer a live "
                "FleetConfig field or fleet stats() key — update "
                "tools/check_docs.py")
        names.append(("process surface", n))
    for n in AUTOSCALE_NAMES:
        if n not in live:
            raise AssertionError(
                f"AUTOSCALE_NAMES lists {n!r}, which is no longer a "
                "live FleetConfig field or fleet stats() key — update "
                "tools/check_docs.py")
        names.append(("autoscale surface", n))
    for n in DISAGG_NAMES:
        if n not in live:
            raise AssertionError(
                f"DISAGG_NAMES lists {n!r}, which is no longer a live "
                "FleetConfig field, fleet stats() key, or recorder "
                "event kind — update tools/check_docs.py")
        names.append(("disagg surface", n))
    for n in SHARED_TIER_NAMES:
        if n not in live:
            raise AssertionError(
                f"SHARED_TIER_NAMES lists {n!r}, which is no longer a "
                "live FleetConfig field, fleet stats() key, or "
                "recorder event kind — update tools/check_docs.py")
        names.append(("shared tier surface", n))
    # the sharded-train surface: liveness from the build_train_step
    # signature, the DistributedFusedAdam dataclass fields, a live
    # world-1 optimizer's stats() keys (the flat geometry is built on
    # first init), and a constructed meshless TrainStep's attributes +
    # public methods — routed to docs/training.md specifically
    import inspect

    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.train import build_train_step

    opt = DistributedFusedAdam(lr=1e-3, flat_mode="global")
    opt.init({"w": jnp.zeros((4,), jnp.float32)})
    ts = build_train_step(lambda p, mb: jnp.sum(p["w"]) * 0.0, opt)
    train_live = set(inspect.signature(build_train_step).parameters)
    train_live |= {f.name for f in dataclasses.fields(DistributedFusedAdam)}
    train_live |= set(opt.stats())
    train_live |= set(vars(ts))
    train_live |= {n for n in dir(type(ts)) if not n.startswith("_")}
    for n in TRAIN_SHARDED_NAMES:
        if n not in train_live:
            raise AssertionError(
                f"TRAIN_SHARDED_NAMES lists {n!r}, which is no longer "
                "a live build_train_step parameter, DistributedFusedAdam "
                "field, stats() key, or TrainStep attribute — update "
                "tools/check_docs.py")
        names.append(("train sharded surface", n))
    return names


def main():
    serving_text = _docs_text(SERVING_DOCS)
    obs_text = _docs_text(OBS_DOCS)
    fleet_text = _docs_text(FLEET_DOCS)
    robustness_text = _docs_text(ROBUSTNESS_DOCS)
    mesh_text = _docs_text(MESH_DOCS)
    train_text = _docs_text(TRAIN_DOCS)
    missing = []
    for kind, name in collect_names():
        if kind in OBS_KINDS:
            text, where = obs_text, OBS_DOCS
        elif kind in FLEET_KINDS:
            text, where = fleet_text, FLEET_DOCS
        elif kind in INTEGRITY_KINDS:
            text, where = robustness_text, ROBUSTNESS_DOCS
        elif kind in MESH_KINDS or kind in WEIGHT_QUANT_KINDS:
            text, where = mesh_text, MESH_DOCS
        elif (kind in PROCESS_KINDS or kind in AUTOSCALE_KINDS
                or kind in DISAGG_KINDS or kind in SHARED_TIER_KINDS):
            text, where = fleet_text, FLEET_DOCS
        elif kind in TRAIN_SHARDED_KINDS:
            text, where = train_text, TRAIN_DOCS
        else:
            text, where = serving_text, SERVING_DOCS
        if name not in text:
            missing.append((kind, name))
            print(f"UNDOCUMENTED {kind}: {name!r} appears in neither "
                  f"{' nor '.join(where)}", file=sys.stderr)
    return missing


if __name__ == "__main__":
    sys.exit(1 if main() else 0)
