/* Host-side data-loader hot path: epoch shuffling, batch row gather,
 * and BERT-style MLM masking over tokenized corpora.
 *
 * The reference ecosystem leaves input pipelines to DALI/torch
 * DataLoader (C++ under the hood); this is the equivalent native tier
 * for the TPU rebuild: branch-light C over preallocated numpy buffers,
 * driven through ctypes (no pybind11 in this toolchain), with a
 * background-thread prefetcher on the Python side overlapping batch
 * assembly with device steps.
 *
 * RNG: SplitMix64 seeding + xorshift64* streams, one stream per call —
 * deterministic for a given (seed, call) pair regardless of batch
 * order, so shuffles and masks are reproducible across runs.
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

static inline uint64_t splitmix64(uint64_t *s) {
    uint64_t z = (*s += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
}

static inline uint64_t xorshift64s(uint64_t *s) {
    uint64_t x = *s;
    x ^= x >> 12;
    x ^= x << 25;
    x ^= x >> 27;
    *s = x;
    return x * 0x2545F4914F6CDD1DULL;
}

/* Unbiased bounded draw (Lemire): uniform in [0, bound). */
static inline uint64_t bounded(uint64_t *s, uint64_t bound) {
    if (bound <= 1) return 0;
    __uint128_t m = (__uint128_t)xorshift64s(s) * bound;
    return (uint64_t)(m >> 64);
}

/* Fill idx with 0..n-1 shuffled (Fisher-Yates). */
void apex_shuffle_indices(uint64_t *idx, size_t n, uint64_t seed) {
    uint64_t st = seed ? seed : 1;
    uint64_t rng = splitmix64(&st);
    if (!rng) rng = 1;
    for (size_t i = 0; i < n; i++) idx[i] = i;
    for (size_t i = n; i > 1; i--) {
        uint64_t j = bounded(&rng, i);
        uint64_t t = idx[i - 1];
        idx[i - 1] = idx[j];
        idx[j] = t;
    }
}

/* Gather rows: out[r] = corpus[idx[r]] for r in [0, n_rows). */
void apex_gather_rows(const int32_t *corpus, size_t row_len,
                      const uint64_t *idx, size_t n_rows, int32_t *out) {
    for (size_t r = 0; r < n_rows; r++)
        memcpy(out + r * row_len, corpus + idx[r] * row_len,
               row_len * sizeof(int32_t));
}

/* BERT MLM masking over a flat token buffer of length n.
 *
 * For each position whose token is not in special[0..n_special):
 *   with probability prob_q16/65536: labels[i] = tokens[i], then
 *     80%: ids[i] = mask_id; 10%: ids[i] = uniform random token;
 *     10%: ids[i] = tokens[i] (unchanged).
 * Every other position: ids[i] = tokens[i], labels[i] = -1.
 */
void apex_mlm_mask(const int32_t *tokens, int32_t *ids, int32_t *labels,
                   size_t n, int32_t vocab_size, int32_t mask_id,
                   const int32_t *special, size_t n_special,
                   uint32_t prob_q16, uint64_t seed) {
    uint64_t st = seed ? seed : 1;
    uint64_t rng = splitmix64(&st);
    if (!rng) rng = 1;
    for (size_t i = 0; i < n; i++) {
        int32_t tok = tokens[i];
        ids[i] = tok;
        labels[i] = -1;
        int is_special = 0;
        for (size_t k = 0; k < n_special; k++)
            if (tok == special[k]) { is_special = 1; break; }
        if (is_special) continue;
        uint64_t r = xorshift64s(&rng);
        if ((uint32_t)(r & 0xFFFF) < prob_q16) {
            labels[i] = tok;
            uint32_t kind = (uint32_t)((r >> 16) % 10); /* 0-7 mask, 8 rnd */
            if (kind < 8)
                ids[i] = mask_id;
            else if (kind == 8)
                ids[i] = (int32_t)bounded(&rng, (uint64_t)vocab_size);
            /* kind == 9: keep original */
        }
    }
}
