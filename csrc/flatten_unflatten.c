/* Host-side flatten/unflatten of tensor buffers.
 *
 * Native analog of the reference's apex_C extension
 * (csrc/flatten_unflatten.cpp, SURVEY.md §2.2): packing a list of
 * tensors into one contiguous buffer and back. On TPU the DEVICE-side
 * packing is XLA's concatenate (see apex_tpu/utils/pytree.py); this
 * C path serves the host-side staging users of apex_C had — checkpoint
 * assembly and host ring buffers — where Python-loop memcpy dominates.
 *
 * Exposed via ctypes (no pybind11 in this toolchain): plain C ABI,
 * pointer arrays built by the Python wrapper in
 * apex_tpu/_native/__init__.py, which also owns the fallback when no
 * compiler is present.
 */

#include <stddef.h>
#include <string.h>

/* Copy n source buffers (srcs[i], nbytes[i]) back-to-back into dst. */
void apex_flatten(const void **srcs, const size_t *nbytes, size_t n,
                  void *dst) {
    char *out = (char *)dst;
    for (size_t i = 0; i < n; ++i) {
        memcpy(out, srcs[i], nbytes[i]);
        out += nbytes[i];
    }
}

/* Split src into n destination buffers of nbytes[i] each. */
void apex_unflatten(const void *src, void **dsts, const size_t *nbytes,
                    size_t n) {
    const char *in = (const char *)src;
    for (size_t i = 0; i < n; ++i) {
        memcpy(dsts[i], in, nbytes[i]);
        in += nbytes[i];
    }
}
