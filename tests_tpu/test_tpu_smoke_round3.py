"""On-hardware smoke for the round-2 late additions: the MoE layer
(routing einsums + grouped expert GEMMs compile and train on the real
chip), the dots remat policy, and the native data loader feeding an
actual device step. Same contract as the other smoke files: real
kernels, auto-skipped off-TPU by conftest."""

import jax
import jax.numpy as jnp
import numpy as np


def test_moe_gpt_train_step_on_chip():
    from apex_tpu.models.gpt import (
        GPTConfig,
        GPTLMHeadModel,
        lm_loss,
        moe_losses_total,
    )
    from apex_tpu.optimizers import FusedAdam

    cfg = GPTConfig.tiny(num_experts=4, moe_top_k=2, dropout=0.0,
                         fused_kernels=True, remat=False,
                         hidden_size=128, num_heads=4)
    model = GPTLMHeadModel(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (4, 64)))
    params = {"params": model.init(jax.random.PRNGKey(0), ids)["params"]}
    opt = FusedAdam(lr=1e-3)
    ost = opt.init(params)

    @jax.jit
    def step(params, ost):
        def loss_fn(p):
            logits, col = model.apply(p, ids, mutable=("losses",))
            return lm_loss(logits, ids) + moe_losses_total(col)

        loss, g = jax.value_and_grad(loss_fn)(params)
        p2, o2 = opt.step(g, ost, params)
        return p2, o2, loss

    losses = []
    for _ in range(5):
        params, ost, loss = step(params, ost)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_bert_dots_remat_policy_on_chip():
    from apex_tpu.models import BertConfig, BertForPreTraining

    cfg = BertConfig.tiny(dtype=jnp.bfloat16, hidden_dropout=0.0,
                          attention_dropout=0.0, remat=True,
                          remat_policy="dots")
    model = BertForPreTraining(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 64)))
    mask = jnp.ones_like(ids)
    params = model.init(jax.random.PRNGKey(0), ids, None, mask)

    def loss(p):
        mlm, nsp = model.apply(p, ids, None, mask)
        return mlm.astype(jnp.float32).mean() + nsp.astype(jnp.float32).mean()

    val, g = jax.jit(jax.value_and_grad(loss))(params)
    jax.block_until_ready(g)
    assert np.isfinite(float(val))


def test_data_loader_feeds_device_step():
    from apex_tpu.data import MLMBatchLoader, native_available

    assert native_available()  # C path must build on the bench machine
    rng = np.random.RandomState(3)
    corpus = rng.randint(5, 500, (64, 32)).astype(np.int32)
    loader = MLMBatchLoader(corpus, batch_size=16, vocab_size=500,
                            mask_id=4, special_ids=[0, 1, 2, 3, 4])

    @jax.jit
    def masked_count(ids, labels):
        return jnp.sum(labels >= 0), jnp.sum(ids)

    total = 0
    for ids_np, labels_np in loader:
        n, _ = masked_count(jnp.asarray(ids_np), jnp.asarray(labels_np))
        total += int(n)
    assert total > 0  # some positions masked, device consumed every batch
