"""On-hardware smoke for the round-3 additions: fused in-kernel
attention dropout (hardware PRNG, fwd + replayed bwd), the fused
elementwise dropout, the single-tile fused attention backward, the
in-kernel masked softmax (any scale), and the LAMB grad_scale fused
tail. Same contract as the other smoke files: real compiled kernels,
auto-skipped off-TPU by conftest."""

import jax
import jax.numpy as jnp
import numpy as np


def test_flash_dropout_native_prng_parity_on_chip():
    from apex_tpu.ops.flash_attention import (
        flash_attention,
        flash_dropout_keep_mask,
        mha_with_mask_reference,
    )

    B, H, S, D = 2, 3, 128, 64
    rate, seed = 0.1, 1234
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, H, S, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, H, S, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, H, S, D), jnp.float32)
    with jax.default_matmul_precision("highest"):
        out = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, None, False, 0.125, rate, seed))(q, k, v)
        keep = flash_dropout_keep_mask(B, H, S, S, rate, seed)
        ref = mha_with_mask_reference(q, k, v, keep, None, False, 0.125,
                                      rate)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5
    kf = float(jnp.mean(keep.astype(jnp.float32)))
    assert abs(kf - 0.9) < 0.02

    # bwd replays the identical mask (single-tile fused bwd at S=128)
    def loss(q):
        return jnp.sum(flash_attention(q, k, v, None, False, 0.125,
                                       rate, seed))

    def loss_ref(q):
        return jnp.sum(mha_with_mask_reference(q, k, v, keep, None,
                                               False, 0.125, rate))

    with jax.default_matmul_precision("highest"):
        g = jax.jit(jax.grad(loss))(q)
        gr = jax.jit(jax.grad(loss_ref))(q)
    assert float(jnp.max(jnp.abs(g - gr))) < 3e-4


def test_split_tile_bwd_still_runs_on_chip():
    """S=640 forces nk=2: the split dq/dkv backward path."""
    from apex_tpu.ops.flash_attention import flash_attention

    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (1, 2, 640, 64), jnp.bfloat16)

    g = jax.jit(jax.grad(lambda q: jnp.sum(flash_attention(
        q, q, q, None, True, 0.125, 0.1, 7).astype(jnp.float32))))(q)
    assert bool(jnp.all(jnp.isfinite(g.astype(jnp.float32))))


def test_fused_elementwise_dropout_on_chip():
    from apex_tpu.ops.dropout import fused_dropout

    x = jnp.ones((16, 512, 256), jnp.bfloat16)
    y1 = jax.jit(lambda x: fused_dropout(x, 0.1, 5))(x)
    y2 = jax.jit(lambda x: fused_dropout(x, 0.1, 5))(x)
    y3 = jax.jit(lambda x: fused_dropout(x, 0.1, 6))(x)
    a1 = np.asarray(y1, np.float32)
    assert (a1 == np.asarray(y2, np.float32)).all()
    assert (a1 != np.asarray(y3, np.float32)).any()
    assert abs((a1 != 0).mean() - 0.9) < 0.01
    # bwd replay
    dx = jax.jit(jax.grad(lambda x: jnp.sum(
        fused_dropout(x, 0.1, 5).astype(jnp.float32))))(x)
    np.testing.assert_array_equal(np.asarray(dx, np.float32) != 0, a1 != 0)


def test_masked_softmax_negative_scale_on_chip():
    from apex_tpu.ops.softmax import scaled_masked_softmax, softmax_reference

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 2, 8, 64).astype("f4"))
    mask = jnp.asarray(rng.rand(2, 1, 8, 64) > 0.6)
    for scale in (-2.0, 1e-6):
        y = jax.jit(lambda x: scaled_masked_softmax(x, mask, scale))(x)
        ref = softmax_reference(x, jnp.broadcast_to(mask, x.shape), scale)
        assert float(jnp.max(jnp.abs(y - ref))) < 1e-5


def test_lamb_grad_scale_fused_tail_on_chip():
    from apex_tpu.optimizers import FusedLAMB

    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(128, 128).astype("f4"))}
    grads = {"w": jnp.asarray(rng.randn(128, 128).astype("f4") * 0.1)}
    scale = 2.0 ** 14
    opt = FusedLAMB(lr=1e-2)
    scaled = jax.tree.map(lambda g: g * scale, grads)

    @jax.jit
    def fused(params, ost):
        return opt.step(scaled, ost, params, grad_scale=scale)

    @jax.jit
    def ref(params, ost):
        return opt.step(grads, ost, params)

    p1, _, found = fused(params, opt.init(params))
    p2, _ = ref(params, opt.init(params))
    assert not bool(found)
    np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p2["w"]),
                               rtol=1e-5, atol=1e-6)
