"""On-hardware smoke for the round-2 components: ring attention, ZeRO
optimizers, contrib MHA, the native extension, and the fp16_utils /
clip_grad / xentropy step pieces. Same contract as test_tpu_smoke.py:
compiles + runs the REAL kernels/collectives (1-device mesh where a mesh
is required); auto-skipped off-TPU by conftest."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P


def test_ring_attention_on_chip_aligned_and_unaligned():
    from apex_tpu.ops.ring_attention import (
        ring_attention,
        ring_attention_reference,
    )

    mesh = jax.make_mesh((1,), ("context",))
    for S in (512, 200):
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(ks[0], (1, 2, S, 64))
        k = jax.random.normal(ks[1], (1, 2, S, 64))
        v = jax.random.normal(ks[2], (1, 2, S, 64))
        km = jnp.zeros((1, S), bool)
        for causal in (False, True):
            out = jax.jit(jax.shard_map(
                lambda q, k, v, km: ring_attention(
                    q, k, v, km, causal, 0.125, axis_name="context"),
                mesh=mesh, in_specs=(P(),) * 4, out_specs=P(),
                check_vma=False))(q, k, v, km)
            with jax.default_matmul_precision("highest"):
                ref = ring_attention_reference(q, k, v, None, causal, 0.125)
            err = float(jnp.max(jnp.abs(out - ref)))
            assert err < 5e-5, (S, causal, err)


def test_ulysses_attention_on_chip():
    from apex_tpu.ops.ulysses_attention import (
        ulysses_attention,
        ulysses_attention_reference,
    )

    mesh = jax.make_mesh((1,), ("context",))
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (1, 4, 384, 64))
    k = jax.random.normal(ks[1], (1, 4, 384, 64))
    v = jax.random.normal(ks[2], (1, 4, 384, 64))
    km = jnp.zeros((1, 384), bool)
    out = jax.jit(jax.shard_map(
        lambda q, k, v, km: ulysses_attention(q, k, v, km, True, 0.125,
                                              axis_name="context"),
        mesh=mesh, in_specs=(P(),) * 4, out_specs=P(),
        check_vma=False))(q, k, v, km)
    with jax.default_matmul_precision("highest"):
        ref = ulysses_attention_reference(q, k, v, None, True, 0.125)
    assert float(jnp.max(jnp.abs(out - ref))) < 5e-5


def test_zero_optimizers_step_on_chip():
    from apex_tpu.contrib.optimizers import (
        DistributedFusedAdam,
        DistributedFusedLAMB,
    )

    mesh = jax.make_mesh((1,), ("data",))
    params = {"w": jnp.ones((512, 384), jnp.bfloat16),
              "b": jnp.ones((384,), jnp.bfloat16)}
    for opt in (DistributedFusedAdam(lr=1e-2, group_size=1),
                DistributedFusedLAMB(lr=1e-2, group_size=1)):
        def f(p):
            g = jax.tree.map(lambda x: x * 0.01, p)
            st = opt.init(p)
            p2, st2 = opt.step(g, st, p)
            return jnp.sum(p2["w"].astype(jnp.float32))[None]

        out = jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=P(), out_specs=P("data")))(params)
        assert np.isfinite(float(out[0]))


def test_contrib_mha_flash_path_on_chip():
    from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

    attn = SelfMultiheadAttn(128, 8, dropout=0.0)
    x = jax.random.normal(jax.random.PRNGKey(0), (384, 2, 128), jnp.bfloat16)
    params = attn.init(jax.random.PRNGKey(1), x, None, False)
    out = jax.jit(lambda p, x: attn.apply(p, x, None, False))(params, x)
    assert out.dtype == jnp.bfloat16
    assert np.isfinite(np.asarray(out, np.float32)).all()


def test_native_extension_on_this_host():
    from apex_tpu import _native

    assert _native.native_available()
    arrays = [np.random.RandomState(0).randn(256, 256).astype("f4"),
              np.arange(7, dtype="i4")]
    flat, metas = _native.flatten(arrays)
    back = _native.unflatten(flat, metas)
    for a, b in zip(arrays, back):
        np.testing.assert_array_equal(a, b)


def test_fp16_optimizer_step_on_chip():
    from apex_tpu.fp16_utils import FP16_Optimizer, network_to_half
    from apex_tpu.optimizers import FusedAdam

    params = network_to_half({"w": jnp.ones((256, 256))})
    opt = FP16_Optimizer(FusedAdam(lr=1e-2), dynamic_loss_scale=True)
    state = opt.init(params)
    grads = jax.tree.map(lambda p: jnp.ones_like(p) * 65536.0, params)
    p2, state, skipped = jax.jit(opt.step)(grads, state, params)
    assert not bool(skipped)
    assert float(jnp.asarray(p2["w"][0, 0], jnp.float32)) < 1.0


def test_clip_grad_and_xentropy_on_chip():
    from apex_tpu.contrib.clip_grad import clip_grad_norm_
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss

    g = {"a": jax.random.normal(jax.random.PRNGKey(0), (512, 512))}
    clipped, norm = jax.jit(lambda g: clip_grad_norm_(g, 1.0))(g)
    assert float(norm) > 1.0
    flat = jnp.concatenate([x.reshape(-1) for x in jax.tree.leaves(clipped)])
    np.testing.assert_allclose(float(jnp.linalg.norm(flat)), 1.0, rtol=1e-3)

    logits = jax.random.normal(jax.random.PRNGKey(1), (64, 1024))
    labels = jax.random.randint(jax.random.PRNGKey(2), (64,), 1, 1024)
    loss = jax.jit(lambda l, y: softmax_cross_entropy_loss(
        l, y, smoothing=0.1))(logits, labels)
    assert np.isfinite(np.asarray(loss)).all()


def test_interleaved_pipeline_on_chip():
    """pp=1 v=2 circular schedule compiles + runs on the real chip."""
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.pipeline_parallel import (
        spmd_pipeline_interleaved,
    )

    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=1, pipeline_model_parallel_size_=1)
    try:
        mesh = parallel_state.get_mesh()
        w = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 64)) * 0.3
        xs = jax.random.normal(jax.random.PRNGKey(1), (4, 2, 64))

        def f(w, xs):
            return spmd_pipeline_interleaved(
                lambda p, x, i: jnp.tanh(x @ p), w, xs,
                num_microbatches=4, num_model_chunks=2)

        out = jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=(P(), P()), out_specs=P("pipeline"),
            check_vma=False))(w, xs)
        assert np.isfinite(np.asarray(out)).all()
    finally:
        parallel_state.destroy_model_parallel()
