"""On-hardware smoke tier (VERDICT round 1, item 3).

The CPU-sim suite in ``tests/`` runs every Pallas kernel in interpreter
mode and pins jax to 8 virtual CPU devices, so the whole class of
real-hardware failures — Mosaic lowering, tiled layouts, runtime buffer
handling — is invisible to it by construction. This tier runs only when a
real TPU is attached (``jax.default_backend() == "tpu"``) and compiles +
executes the actual kernels and a real mixed-precision train step.

Run with:  python -m pytest tests_tpu/ -q      (on the TPU machine)
It auto-skips everywhere else, so CI-sim behavior is unchanged.

Mirrors the intent of the reference's L0 tier (``tests/L0/run_*`` (U),
SURVEY.md §4), which runs on the actual accelerator.
"""

import jax
import pytest


def pytest_collection_modifyitems(config, items):
    if jax.default_backend() != "tpu":
        skip = pytest.mark.skip(reason="on-TPU smoke tier: no TPU attached")
        for item in items:
            item.add_marker(skip)
