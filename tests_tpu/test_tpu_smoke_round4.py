"""On-hardware smoke for this session's additions: the hysteresis
scaler inside a compiled train step and the fused l2norm_scale op.
Same contract as the other smoke files: real compiled path,
auto-skipped off-TPU by conftest."""

import jax
import jax.numpy as jnp
import numpy as np


def test_hysteresis_scaler_step_on_chip():
    """A jitted O2-style step with LossScaler(hysteresis=2): the first
    overflow holds the scale (step skipped), the second backs off —
    all as in-graph selects, no host callbacks (axon-safe)."""
    from apex_tpu.amp import LossScaler
    from apex_tpu.optimizers import FusedAdam

    params = {"w": jnp.ones((256, 256), jnp.bfloat16)}
    opt = FusedAdam(lr=1e-3).with_master_weights(True)
    scaler = LossScaler(hysteresis=2)
    ost = opt.init(params)
    sst = scaler.init()
    x = jnp.asarray(np.random.RandomState(0).randn(16, 256), jnp.bfloat16)

    @jax.jit
    def step(params, ost, sst, poison):
        def loss_fn(p):
            h = jnp.tanh(x @ p["w"])
            return jnp.mean(h.astype(jnp.float32) ** 2) * poison

        (loss, found), grads = scaler.value_and_grad(loss_fn, sst)(params)
        p2, ost2 = opt.step(grads, ost, params, skip_if=found)
        return p2, ost2, scaler.update(sst, found), loss

    params, ost, sst, _ = step(params, ost, sst, 1.0)
    w_before = params["w"]
    params, ost, sst, _ = step(params, ost, sst, jnp.inf)
    assert float(sst.loss_scale) == 2.0 ** 16      # held (tolerance 2->1)
    assert int(sst.steps_skipped) == 1
    assert bool(jnp.all(params["w"] == w_before))  # step skipped
    params, ost, sst, _ = step(params, ost, sst, jnp.inf)
    assert float(sst.loss_scale) == 2.0 ** 15      # depleted: backed off
    params, ost, sst, _ = step(params, ost, sst, 1.0)
    assert not bool(jnp.all(params["w"] == w_before))  # training resumed


def test_l2norm_scale_compiles_on_chip():
    """multi_tensor_l2norm_scale at aligned AND unaligned shapes."""
    from apex_tpu.multi_tensor_apply import multi_tensor_applier
    from apex_tpu.ops import multi_tensor as mt

    rng = np.random.RandomState(1)
    xs = [jnp.asarray(rng.randn(512, 128).astype("f4")),
          jnp.asarray(rng.randn(1000, 7).astype("f4")),           # unaligned
          jnp.asarray(rng.randn(33), jnp.bfloat16)]               # mixed dtype

    @jax.jit
    def f(xs):
        return multi_tensor_applier(
            mt.multi_tensor_l2norm_scale, None,
            [xs, [jnp.zeros_like(x) for x in xs]], 0.25, per_tensor=True)

    outs, gnorm, per, flag = f(xs)
    ref = np.sqrt(sum(float(np.sum((np.asarray(x) * 0.25) ** 2))
                      for x in xs))
    np.testing.assert_allclose(float(gnorm), ref, rtol=1e-5)
    assert not bool(flag)
    for x, o in zip(xs, outs):
        np.testing.assert_allclose(np.asarray(o), np.asarray(x) * 0.25,
                                   rtol=1e-6)
