"""Round-7-vintage on-chip smokes (round 5 of the build): the
interleaved pipeline schedule compiled for the real TPU, and the
round-5 LN hybrid training dispatch on real Mosaic kernels."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P


def test_interleaved_schedule_compiles_and_runs_on_chip():
    """VERDICT r4 weak #5: the interleaved schedule had no on-chip
    test. One chip = a pp=1 mesh with v=2 virtual chunks — the
    wraparound-ppermute circular schedule compiled by the real TPU
    backend (CPU-sim covers pp>1; the single-chip compile covers the
    Mosaic/XLA:TPU lowering of the scan + dynamic indexing)."""
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_with_interleaving,
    )

    pp, V, M, MB, H = 1, 2, 4, 2, 64
    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=pp,
        virtual_pipeline_model_parallel_size_=V,
        devices=jax.devices()[:pp])
    try:
        mesh = parallel_state.get_mesh()
        rng = np.random.RandomState(0)
        ws = jnp.asarray(rng.randn(V, pp, H, H).astype("f4") * 0.3)
        xs = jnp.asarray(rng.randn(M, MB, H).astype("f4"))
        ts = jnp.asarray(rng.randn(M, MB, H).astype("f4"))

        def stage_fn(w, x, mb_idx):
            return jnp.tanh(x @ w)

        def train_step(w_local, xs, ts):
            w = w_local.reshape(V, H, H)

            def loss_fn(out, mb_idx):
                t = jax.lax.dynamic_index_in_dim(ts, mb_idx,
                                                 keepdims=False)
                return jnp.mean((out - t) ** 2)

            loss, grads = forward_backward_pipelining_with_interleaving(
                stage_fn, xs, w, num_microbatches=M, loss_fn=loss_fn)
            return loss, (w - 1e-2 * grads)[:, None]

        loss, w2 = jax.jit(jax.shard_map(
            train_step, mesh=mesh,
            in_specs=(P(None, "pipeline"), P(), P()),
            out_specs=(P(), P(None, "pipeline"))))(ws, xs, ts)
        assert np.isfinite(float(loss))
        assert not np.array_equal(np.asarray(w2[:, 0]), np.asarray(ws))
    finally:
        parallel_state.destroy_model_parallel()


def test_ln_hybrid_training_dispatch_on_chip():
    """The round-5 LN training dispatch (jnp fwd + Pallas bwd) on real
    kernels: value matches the jnp formula, grads match the jnp
    autodiff to bf16-scaled tolerance, and dgamma/dbeta come from the
    Pallas backward."""
    from apex_tpu.ops.layer_norm import (
        fused_layer_norm_affine,
        layer_norm_reference,
    )

    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(256, 1024).astype("f4")).astype(jnp.bfloat16)
    w = jnp.asarray(rng.rand(1024).astype("f4") + 0.5)
    b = jnp.asarray(rng.randn(1024).astype("f4"))

    def loss_fused(x, w, b):
        return jnp.sum(fused_layer_norm_affine(x, w, b)
                       .astype(jnp.float32) ** 2)

    def loss_ref(x, w, b):
        return jnp.sum(layer_norm_reference(x, w, b)
                       .astype(jnp.float32) ** 2)

    gf = jax.jit(jax.grad(loss_fused, argnums=(0, 1, 2)))(x, w, b)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(x, w, b)
    for a, c, tol in zip(gf, gr, (3e-2, 2e-1, 2e-1)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(c, np.float32),
            atol=tol, rtol=3e-2)
