"""On-TPU smoke tests: every Pallas kernel fwd+bwd at aligned AND
unaligned shapes, compiled by Mosaic and executed on the chip, plus one
tiny end-to-end O2 + FusedLAMB train step.

These are the exact failure classes that round 1's CPU-only suite missed:
Mosaic lowering gaps (scatter), tiled-layout blowups, and runtime buffer
semantics on the axon PJRT backend.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# (rows, hidden): aligned to (8,128) tiles, and deliberately unaligned.
LN_SHAPES = [(64, 256), (64, 100), (57, 768), (3, 257)]
# (batch, heads, q, k) for the softmax family.
SM_SHAPES = [(2, 4, 128, 128), (2, 4, 100, 100), (1, 2, 37, 64)]


def _max_err(a, b):
    return float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))


@pytest.mark.parametrize("shape", LN_SHAPES)
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_layer_norm_fwd_bwd_compiles_and_matches(shape, dtype):
    from apex_tpu.ops.layer_norm import (
        fused_layer_norm_affine, layer_norm_reference)

    n, h = shape
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (n, h), dtype)
    w = jnp.ones((h,), jnp.float32) + 0.1
    b = jnp.full((h,), 0.05, jnp.float32)

    y = jax.jit(fused_layer_norm_affine)(x, w, b)
    y_ref = layer_norm_reference(x, w, b)
    assert _max_err(y, y_ref) < (0.03 if dtype == jnp.bfloat16 else 1e-4)

    def f(x, w, b):
        return jnp.sum(fused_layer_norm_affine(x, w, b) * 1.7)

    def fr(x, w, b):
        return jnp.sum(layer_norm_reference(x, w, b) * 1.7)

    g = jax.jit(jax.grad(f, argnums=(0, 1, 2)))(x, w, b)
    gr = jax.jit(jax.grad(fr, argnums=(0, 1, 2)))(x, w, b)
    tol = 0.06 if dtype == jnp.bfloat16 else 1e-3
    for a, r in zip(g, gr):
        assert _max_err(a, r) < tol


@pytest.mark.parametrize("shape", LN_SHAPES[:2])
def test_rms_norm_fwd_bwd_compiles_and_matches(shape):
    from apex_tpu.ops.layer_norm import fused_rms_norm_affine, rms_norm_reference

    n, h = shape
    x = jax.random.normal(jax.random.PRNGKey(1), (n, h), jnp.bfloat16)
    w = jnp.ones((h,), jnp.float32) + 0.1

    y = jax.jit(fused_rms_norm_affine)(x, w)
    assert _max_err(y, rms_norm_reference(x, w)) < 0.03

    g = jax.jit(jax.grad(lambda x, w: jnp.sum(fused_rms_norm_affine(x, w)),
                         argnums=(0, 1)))(x, w)
    gr = jax.jit(jax.grad(lambda x, w: jnp.sum(rms_norm_reference(x, w)),
                          argnums=(0, 1)))(x, w)
    for a, r in zip(g, gr):
        assert _max_err(a, r) < 0.06


@pytest.mark.parametrize("shape", SM_SHAPES)
def test_scaled_masked_softmax_fwd_bwd(shape):
    from apex_tpu.ops.softmax import scaled_masked_softmax, softmax_reference

    b, h, q, k = shape
    x = jax.random.normal(jax.random.PRNGKey(2), shape, jnp.bfloat16)
    mask = (jax.random.uniform(jax.random.PRNGKey(3), (b, 1, q, k)) < 0.2)

    y = jax.jit(lambda x, m: scaled_masked_softmax(x, m, 0.5))(x, mask)
    y_ref = softmax_reference(x, mask, 0.5)
    assert _max_err(y, y_ref) < 0.02

    g = jax.jit(jax.grad(
        lambda x: jnp.sum(scaled_masked_softmax(x, mask, 0.5) * 1.3)))(x)
    gr = jax.jit(jax.grad(
        lambda x: jnp.sum(softmax_reference(x, mask, 0.5) * 1.3)))(x)
    assert _max_err(g, gr) < 0.03


@pytest.mark.parametrize("shape", SM_SHAPES[:2])
def test_upper_triang_softmax_fwd_bwd(shape):
    from apex_tpu.ops.softmax import (
        scaled_upper_triang_masked_softmax, softmax_reference)

    x = jax.random.normal(jax.random.PRNGKey(4), shape, jnp.bfloat16)
    y = jax.jit(lambda x: scaled_upper_triang_masked_softmax(x, 0.7))(x)
    y_ref = softmax_reference(x, None, 0.7, causal=True)
    assert _max_err(y, y_ref) < 0.02

    g = jax.jit(jax.grad(
        lambda x: jnp.sum(scaled_upper_triang_masked_softmax(x, 0.7))))(x)
    gr = jax.jit(jax.grad(
        lambda x: jnp.sum(softmax_reference(x, None, 0.7, causal=True))))(x)
    assert _max_err(g, gr) < 0.03


def test_tiny_bert_o2_fused_lamb_train_step():
    """End-to-end: tiny BERT, amp O2, FusedLAMB, fused kernels, real chip."""
    import apex_tpu.amp as amp
    from apex_tpu.models import BertConfig, BertForPreTraining, pretraining_loss
    from apex_tpu.optimizers import FusedLAMB

    cfg = BertConfig.tiny(dtype=jnp.bfloat16, fused_kernels=True,
                          hidden_dropout=0.0, attention_dropout=0.0)
    model = BertForPreTraining(cfg)
    rng = np.random.RandomState(0)
    B, S = 2, 16
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)))
    types = jnp.zeros((B, S), jnp.int32)
    attn = jnp.ones((B, S), jnp.int32)
    mlm_labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)))
    nsp_labels = jnp.asarray(rng.randint(0, 2, (B,)))

    params = model.init(jax.random.PRNGKey(0), ids, types, attn)["params"]
    opt = FusedLAMB(lr=1e-3, weight_decay=0.01)
    params, opt, handle = amp.initialize(params, opt, opt_level="O2",
                                         verbosity=0)
    ost, sst = opt.init(params), handle.init_state()

    @jax.jit
    def step(params, ost, sst):
        def loss_fn(p):
            mlm, nsp = model.apply({"params": p}, ids, types, attn)
            return pretraining_loss(mlm, nsp, mlm_labels, nsp_labels)

        (loss, found), grads = handle.value_and_grad(loss_fn, sst)(params)
        p2, ost2 = opt.step(grads, ost, params, skip_if=found)
        return p2, ost2, handle.scalers[0].update(sst, found), loss

    losses = []
    for _ in range(5):
        params, ost, sst, loss = step(params, ost, sst)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
    assert float(sst.loss_scale) == 65536.0  # no spurious overflow backoff


def test_multi_tensor_ops_on_chip():
    """scale / l2norm / adam execute compiled (not interpreted) on TPU."""
    from apex_tpu.ops.multi_tensor import (
        ADAM_MODE_ADAMW, multi_tensor_adam, multi_tensor_l2norm,
        multi_tensor_scale)

    ts = [jax.random.normal(jax.random.PRNGKey(i), s)
          for i, s in enumerate([(17,), (8, 128), (3, 5, 7)])]
    outs, flag = jax.jit(
        lambda ts: multi_tensor_scale(0, None, [ts, ts], 0.25))(ts)
    assert not bool(flag)
    for o, t in zip(outs, ts):
        np.testing.assert_allclose(np.asarray(o), np.asarray(t) * 0.25,
                                   rtol=1e-6)

    gn, per = jax.jit(
        lambda ts: multi_tensor_l2norm(0, None, [ts], per_tensor=True))(ts)
    ref = np.sqrt(sum(float(jnp.sum(t.astype(jnp.float32) ** 2)) for t in ts))
    assert abs(float(gn) - ref) < 1e-2

    g = [jnp.full_like(t, 0.1) for t in ts]
    m = [jnp.zeros_like(t) for t in ts]
    v = [jnp.zeros_like(t) for t in ts]
    (p2, m2, v2) = jax.jit(lambda g, p, m, v: multi_tensor_adam(
        0, None, [g, p, m, v], 1e-2, 0.9, 0.999, 1e-8, 1,
        ADAM_MODE_ADAMW, True, 0.0))(g, ts, m, v)
    for a, b in zip(p2, ts):
        assert _max_err(a, b) > 1e-5  # params moved


@pytest.mark.parametrize("shape,causal,use_mask", [
    ((2, 4, 128, 64), False, True),
    ((1, 2, 512, 64), False, True),
    ((1, 2, 640, 64), True, False),      # multi-block online softmax
    ((1, 1, 100, 64), False, True),      # unaligned
])
def test_flash_attention_fwd_bwd_on_chip(shape, causal, use_mask):
    from apex_tpu.ops.flash_attention import flash_attention, mha_reference

    B, H, S, D = shape
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (B, H, S, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, H, S, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, H, S, D), jnp.bfloat16)
    km = ((jax.random.uniform(jax.random.PRNGKey(9), (B, S)) < 0.3)
          if use_mask else None)
    scale = 1.0 / np.sqrt(D)

    out = jax.jit(lambda q, k, v: flash_attention(q, k, v, km, causal, scale))(
        q, k, v)
    ref = mha_reference(q.astype(jnp.float32), k.astype(jnp.float32),
                        v.astype(jnp.float32), km, causal, scale)
    assert _max_err(out, ref) < 0.02

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, km, causal, scale)
                       .astype(jnp.float32))

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    for a in g:
        assert bool(jnp.all(jnp.isfinite(a.astype(jnp.float32))))
