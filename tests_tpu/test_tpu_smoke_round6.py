"""On-hardware smoke for the round-4 additions: the dropout-enabled
``flash_attention_with_lse`` kernel path (fused in-kernel PRNG dropout
composing with the lse output and its cotangent — the ring-attention
building block, which CPU tests only exercise through the jnp
fallback). Same contract as the other smoke files: real compiled
kernels, auto-skipped off-TPU by conftest."""

import jax
import jax.numpy as jnp
import numpy as np


def test_flash_with_lse_dropout_parity_on_chip():
    """Kernel-path (hardware PRNG) fwd parity of the (out, lse) entry at
    dropout 0.1 against composed attention with the SAME keep-mask; lse
    must stay pre-dropout."""
    from apex_tpu.ops.flash_attention import (
        flash_attention_with_lse,
        flash_dropout_keep_mask,
        mha_with_mask_reference,
    )

    B, H, S, D = 2, 4, 256, 64
    rate, seed = 0.1, 4242
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, H, S, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, H, S, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, H, S, D), jnp.float32)
    with jax.default_matmul_precision("highest"):
        out, lse = jax.jit(lambda q, k, v: flash_attention_with_lse(
            q, k, v, None, False, 0.125, rate, seed))(q, k, v)
        keep = flash_dropout_keep_mask(B, H, S, S, rate, seed)
        ref = mha_with_mask_reference(q, k, v, keep, None, False, 0.125,
                                      rate)
        # pre-dropout lse: composed logsumexp, no keep-mask anywhere
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * 0.125
        lse_ref = jax.nn.logsumexp(s, axis=-1)[:, :, None, :]
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5
    assert float(jnp.max(jnp.abs(lse - lse_ref))) < 2e-5


def test_flash_with_lse_dropout_grads_with_lse_cotangent_on_chip():
    """Backward with BOTH cotangents live (out and lse) at dropout>0:
    the delta - dlse fold and the replayed keep-mask must compose (the
    first time these two features meet is this path; the ring backward
    exercises exactly this combination on real meshes)."""
    from apex_tpu.ops.flash_attention import (
        flash_attention_with_lse,
        flash_dropout_keep_mask,
    )

    B, H, S, D = 2, 4, 256, 64
    rate, seed = 0.1, 4242
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (B, H, S, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, H, S, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, H, S, D), jnp.float32)
    keep = flash_dropout_keep_mask(B, H, S, S, rate, seed)

    def loss_fused(q, k, v):
        out, lse = flash_attention_with_lse(q, k, v, None, False, 0.125,
                                            rate, seed)
        return jnp.sum(jnp.sin(out)) + 0.1 * jnp.sum(jnp.cos(lse))

    def loss_ref(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * 0.125
        lse = jax.nn.logsumexp(s, axis=-1)[:, :, None, :]
        p = jnp.exp(s - lse.transpose(0, 1, 3, 2))
        p = jnp.where(keep, p, 0.0) / (1 - rate)
        out = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        return jnp.sum(jnp.sin(out)) + 0.1 * jnp.sum(jnp.cos(lse))

    with jax.default_matmul_precision("highest"):
        g = jax.jit(jax.grad(loss_fused, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip("qkv", g, gr):
        assert float(jnp.max(jnp.abs(a - b))) < 5e-4, name
    assert all(bool(jnp.all(jnp.isfinite(x))) for x in g)


def test_flash_bsh_bitwise_matches_transposed_on_chip():
    """The (B, S, NH*D)-layout head-pair kernels must produce BITWISE
    the same outputs, gradients, and hardware-PRNG dropout masks as the
    transposed (B, NH, S, D) entry at the flagship shape."""
    from apex_tpu.ops.flash_attention import (
        flash_attention,
        flash_attention_bsh,
    )

    B, S, NH, D = 2, 512, 16, 64
    H = NH * D
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (B, S, H), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, S, H), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, S, H), jnp.bfloat16)

    def split(t):
        return t.reshape(B, S, NH, D).transpose(0, 2, 1, 3)

    def merge(t):
        return t.transpose(0, 2, 1, 3).reshape(B, S, H)

    rate, seed = 0.1, 77
    out = jax.jit(lambda q, k, v: flash_attention_bsh(
        q, k, v, None, NH, False, 0.125, rate, seed))(q, k, v)
    ref = jax.jit(lambda q, k, v: merge(flash_attention(
        split(q), split(k), split(v), None, False, 0.125, rate,
        seed)))(q, k, v)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def loss(f, q):
        return jnp.sum(f(q).astype(jnp.float32) ** 2)

    g1 = jax.jit(jax.grad(lambda q: loss(
        lambda a: flash_attention_bsh(a, k, v, None, NH, False, 0.125,
                                      rate, seed), q)))(q)
    g2 = jax.jit(jax.grad(lambda q: loss(
        lambda a: merge(flash_attention(split(a), split(k), split(v),
                                        None, False, 0.125, rate, seed)),
        q)))(q)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))


def test_flash_with_lse_dropout_tiled_path_on_chip():
    """S=640 forces the SPLIT dq/dkv backward (nk=2): fused dropout
    replay + the lse-cotangent delta fold must compose on the TILED
    kernels too — the path a long-context ring shard (S_local > 512)
    takes on real hardware."""
    from apex_tpu.ops.flash_attention import (
        flash_attention_with_lse,
        flash_dropout_keep_mask,
    )

    B, H, S, D = 1, 2, 640, 64
    rate, seed = 0.1, 555
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (B, H, S, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, H, S, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, H, S, D), jnp.float32)
    keep = flash_dropout_keep_mask(B, H, S, S, rate, seed)

    def loss_fused(q, k, v):
        out, lse = flash_attention_with_lse(q, k, v, None, False, 0.125,
                                            rate, seed)
        return jnp.sum(jnp.sin(out)) + 0.1 * jnp.sum(jnp.cos(lse))

    def loss_ref(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * 0.125
        lse = jax.nn.logsumexp(s, axis=-1)[:, :, None, :]
        p = jnp.exp(s - lse.transpose(0, 1, 3, 2))
        p = jnp.where(keep, p, 0.0) / (1 - rate)
        out = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        return jnp.sum(jnp.sin(out)) + 0.1 * jnp.sum(jnp.cos(lse))

    with jax.default_matmul_precision("highest"):
        vf = jax.jit(loss_fused)(q, k, v)
        vr = jax.jit(loss_ref)(q, k, v)
        g = jax.jit(jax.grad(loss_fused, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    assert abs(float(vf) - float(vr)) < 1e-3
    for name, a, b in zip("qkv", g, gr):
        assert float(jnp.max(jnp.abs(a - b))) < 5e-4, name


def test_ring_attention_dropout_compiled_on_chip():
    """Ring attention with fused dropout on the real chip (cp=1 ring —
    the scan/merge/seed-hash code compiled by Mosaic+XLA, single
    device): matches composed attention with the block's keep-mask."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.ops.flash_attention import (
        flash_dropout_keep_mask,
        mha_with_mask_reference,
    )
    from apex_tpu.ops.ring_attention import _block_seed, ring_attention

    B, H, S, D = 2, 2, 256, 64
    rate, seed = 0.1, 321
    ks = jax.random.split(jax.random.PRNGKey(4), 3)
    q = jax.random.normal(ks[0], (B, H, S, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, H, S, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, H, S, D), jnp.float32)
    mesh = jax.make_mesh((1,), ("context",))

    def f(q, k, v):
        return ring_attention(q, k, v, None, False, 0.125,
                              axis_name="context", dropout_rate=rate,
                              dropout_seed=seed)

    with jax.default_matmul_precision("highest"):
        out = jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=(P(), P(), P()),
            out_specs=P(None, None, "context")))(q, k, v)
        keep = flash_dropout_keep_mask(
            B, H, S, S, rate,
            _block_seed(seed, jnp.int32(0), jnp.int32(0), 1))
        ref = mha_with_mask_reference(q, k, v, keep, None, False, 0.125,
                                      rate)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-4
