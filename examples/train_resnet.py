"""ImageNet-style ResNet training: amp + DDP + SyncBatchNorm end to end.

The rebuild's analog of the reference's ``examples/imagenet/main_amp.py``
(U) — the script that wires every "core" apex surface together on a conv
workload: ``amp.initialize`` opt levels over a ResNet, DDP gradient
synchronization over the ``data`` mesh axis, cross-replica BatchNorm
(the ``convert_syncbn_model`` role, here via the model's
``bn_group``/``axis_name`` knobs), FusedSGD with momentum + weight decay
(the ImageNet recipe), and the dynamic loss scaler.

The sandbox has no network (and no ImageNet); data is synthetic
class-dependent Gaussian images. The data flow, sharding, and amp
machinery are the point.

Run (uses every local device as a data-parallel replica)::

    python examples/train_resnet.py --arch tiny --steps 20
    python examples/train_resnet.py --arch resnet50 --opt-level O2 \
        --batch-size 64 --steps 10

On the 8-device CPU sim::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_resnet.py --arch tiny
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
from apex_tpu.models import ResNet, ResNetConfig
from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import DistributedDataParallel


def synthetic_imagenet(n, image_size, num_classes, seed=0):
    """Class-separable NHWC Gaussian images standing in for ImageNet."""
    rng = np.random.RandomState(seed)
    centers = rng.randn(num_classes, 1, 1, 3).astype("float32")
    labels = rng.randint(0, num_classes, n)
    images = (centers[labels]
              + 0.5 * rng.randn(n, image_size, image_size, 3)).astype("f4")
    return images, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--arch", default="tiny", choices=["tiny", "resnet50"])
    ap.add_argument("--opt-level", default="O2",
                    choices=["O0", "O1", "O2", "O3"])
    ap.add_argument("--batch-size", type=int, default=32,
                    help="GLOBAL batch (split across data-parallel replicas)")
    ap.add_argument("--image-size", type=int, default=32)
    ap.add_argument("--num-classes", type=int, default=10)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--weight-decay", type=float, default=1e-4)
    ap.add_argument("--no-sync-bn", action="store_true",
                    help="local (per-replica) BN stats instead of SyncBN")
    ap.add_argument("--delay-allreduce", action="store_true",
                    help="DDP flat-buffer path (one allreduce after "
                         "backward) instead of bucketed")
    args = ap.parse_args()

    world = jax.device_count()
    if args.batch_size % world:
        raise SystemExit(f"--batch-size {args.batch_size} must divide by "
                         f"the {world} data-parallel replicas")
    mesh = jax.make_mesh((world,), ("data",))
    print(f"backend={jax.default_backend()} replicas={world} "
          f"opt_level={args.opt_level} arch={args.arch}")

    maker = (ResNetConfig.resnet50 if args.arch == "resnet50"
             else ResNetConfig.tiny)
    cfg = maker(num_classes=args.num_classes,
                bn_group=1 if args.no_sync_bn else world,
                axis_name=None if args.no_sync_bn else "data")
    model = ResNet(cfg)

    images, labels = synthetic_imagenet(
        8 * args.batch_size, args.image_size, args.num_classes)

    x0 = jnp.zeros((1, args.image_size, args.image_size, 3))
    variables = model.init(jax.random.PRNGKey(0), x0, train=False)
    params, bstats = variables["params"], variables["batch_stats"]

    opt = FusedSGD(lr=args.lr, momentum=args.momentum,
                   weight_decay=args.weight_decay)
    # O2 default keeps BatchNorm fp32 (keep_batchnorm_fp32) — the BN
    # params/stats of this model are fp32 already; amp casts the rest.
    params, opt, handle = amp.initialize(params, opt,
                                         opt_level=args.opt_level)
    ddp = DistributedDataParallel(axis_name="data",
                                  delay_allreduce=args.delay_allreduce)
    opt_state = opt.init(params)
    scaler_state = handle.init_state()
    # compute_dtype already resolves to cast_model_type when set, else
    # the O1 autocast dtype (bf16), else fp32 for O0
    compute_dtype = handle.properties.compute_dtype

    def train_step(params, bstats, opt_state, scaler_state, x, y):
        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": bstats},
                x.astype(compute_dtype), train=True,
                mutable=["batch_stats"])
            loss = jnp.mean(softmax_cross_entropy_loss(
                logits, y, padding_idx=-1))
            acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
            return loss, (mut["batch_stats"], acc)

        vg = handle.value_and_grad(loss_fn, scaler_state, has_aux=True)
        (loss, found_inf, (new_bstats, acc)), grads = vg(params)
        grads = ddp.allreduce_grads(grads)
        found_inf = jax.lax.pmax(found_inf.astype(jnp.int32), "data") > 0
        new_params, new_opt_state = opt.step(
            grads, opt_state, params, skip_if=found_inf)
        new_scaler_state = handle.update_scale(scaler_state, found_inf)
        # make the updated running stats provably replicated: a no-op
        # under SyncBN (stats already agree), a cross-replica average
        # under --no-sync-bn (torch DDP would keep rank-local stats and
        # save rank 0's; averaging is the single-host analog)
        new_bstats = jax.tree.map(lambda s: jax.lax.pmean(s, "data"),
                                  new_bstats)
        loss = jax.lax.pmean(loss, "data")
        acc = jax.lax.pmean(acc, "data")
        return (new_params, new_bstats, new_opt_state, new_scaler_state,
                loss, acc)

    sharded_step = jax.jit(jax.shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P(), P(), P("data"), P("data")),
        out_specs=(P(), P(), P(), P(), P(), P())))

    nbatches = len(images) // args.batch_size
    for step in range(args.steps):
        i = step % nbatches
        x = jnp.asarray(images[i * args.batch_size:(i + 1) * args.batch_size])
        y = jnp.asarray(labels[i * args.batch_size:(i + 1) * args.batch_size])
        prev = scaler_state
        (params, bstats, opt_state, scaler_state, loss, acc) = sharded_step(
            params, bstats, opt_state, scaler_state, x, y)
        handle.scalers[0].host_overflow_report(prev, scaler_state)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step:4d} loss {float(loss):.4f} "
                  f"top1 {float(acc):.3f} "
                  f"scale {float(scaler_state.loss_scale):.0f}")

    print(f"final loss {float(loss):.4f} top1 {float(acc):.3f}")
    return float(loss)


if __name__ == "__main__":
    main()
