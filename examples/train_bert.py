"""BERT pretraining through the flagship stack: amp O2 (bf16 + fp32
masters) + FusedLAMB + Pallas fused kernels (+ optional data-parallel
mesh) — the BASELINE configs[4] workload at selectable size.

The rebuild's analog of the reference's MLPerf-BERT harness entry point
(SURVEY.md §6). Synthetic token data (no network in the sandbox); the
data flow, kernels, and amp/optimizer machinery are the real thing.

Run::

    python examples/train_bert.py --config tiny --steps 10
    python examples/train_bert.py --config large --batch-size 8 --seq 128
    python examples/train_bert.py --config tiny --data-parallel  # dp mesh

Works on CPU (tiny) and a TPU chip (tiny/base/large) unchanged.
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models import BertConfig, BertForPreTraining
from apex_tpu.models.bert import pretraining_loss
from apex_tpu.optimizers import FusedLAMB


def synthetic_batch(cfg, batch, seq, seed):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq))
    labels = np.where(rng.rand(batch, seq) < 0.15,
                      rng.randint(0, cfg.vocab_size, (batch, seq)), -1)
    nsp = rng.randint(0, 2, (batch,))
    mask = np.ones((batch, seq), np.int32)
    return (jnp.asarray(ids), jnp.asarray(labels), jnp.asarray(nsp),
            jnp.asarray(mask))


def make_loader(cfg, batch, seq, steps):
    """Real input pipeline over a synthetic corpus: C-path shuffle +
    row gather + MLM masking with background prefetch
    (apex_tpu.data.MLMBatchLoader)."""
    from apex_tpu.data import MLMBatchLoader

    # fixed-size corpus cycled over epochs (set_epoch reshuffles+remasks)
    # — constant host memory no matter how many steps
    n_rows = min(max(batch * steps, batch), max(batch, 4096))
    rng = np.random.RandomState(1234)
    corpus = rng.randint(5, cfg.vocab_size, (n_rows, seq)).astype(np.int32)
    corpus[:, 0] = 1  # [CLS]-slot analog, never masked
    return MLMBatchLoader(corpus, batch_size=batch,
                          vocab_size=cfg.vocab_size, mask_id=4,
                          special_ids=[0, 1, 2, 3, 4], prefetch=2)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="tiny",
                    choices=["tiny", "base", "large"])
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--lr", type=float, default=1e-4)
    ap.add_argument("--data-parallel", action="store_true",
                    help="shard the batch over all devices (dp mesh)")
    args = ap.parse_args()

    maker = {"tiny": BertConfig.tiny, "base": BertConfig.bert_base,
             "large": BertConfig.bert_large}[args.config]
    cfg = maker(dtype=jnp.bfloat16, hidden_dropout=0.0,
                attention_dropout=0.0,
                max_position_embeddings=max(args.seq, 512))
    model = BertForPreTraining(cfg)
    print(f"backend={jax.default_backend()} config={args.config} "
          f"B={args.batch_size} S={args.seq} dp={args.data_parallel}")

    ids, labels, nsp, mask = synthetic_batch(
        cfg, args.batch_size, args.seq, 0)
    params = model.init(jax.random.PRNGKey(0), ids, None, mask)

    # O2: bf16 model, fp32 masters inside FusedLAMB, dynamic scaler
    params, optimizer, handle = amp.initialize(
        params, FusedLAMB(lr=args.lr), opt_level="O2",
        cast_model_type=jnp.bfloat16)

    def build_step():
        def step(params, opt_state, scaler_state, ids, labels, nsp, mask):
            def loss_fn(p):
                mlm, nspl = model.apply(p, ids, None, mask)
                return pretraining_loss(mlm, nspl, labels, nsp)

            vg = handle.value_and_grad(loss_fn, scaler_state)
            (loss, found_inf), grads = vg(params)
            if args.data_parallel:
                grads = jax.tree.map(
                    lambda g: jax.lax.pmean(g, "data"), grads)
                found_inf = jax.lax.pmax(
                    found_inf.astype(jnp.int32), "data").astype(bool)
            new_params, new_opt = optimizer.step(
                grads, opt_state, params, skip_if=found_inf)
            new_scaler = handle.update_scale(scaler_state, found_inf)
            if args.data_parallel:
                loss = jax.lax.pmean(loss, "data")
            return new_params, new_opt, new_scaler, loss

        return step

    opt_state = optimizer.init(params)
    scaler_state = handle.init_state()
    step_fn = build_step()

    if args.data_parallel:
        mesh = jax.make_mesh((jax.device_count(),), ("data",))
        data_specs = (P("data"), P("data"), P("data"), P("data"))
        step_fn = jax.shard_map(
            step_fn, mesh=mesh,
            in_specs=(P(), P(), P()) + data_specs,
            out_specs=(P(), P(), P(), P()))
    # no donate_argnums: buffer donation trips a runtime INVALID_ARGUMENT
    # on the axon PJRT backend (see bench.py); XLA still reuses buffers
    # where it can without the annotation
    step_fn = jax.jit(step_fn)

    loader = make_loader(cfg, args.batch_size, args.seq, args.steps)
    nsp_rng = np.random.RandomState(99)
    batches = iter(loader)

    def next_batch():
        nonlocal batches
        try:
            return next(batches)
        except StopIteration:  # epoch boundary: reshuffle + remask
            loader.set_epoch(loader.epoch + 1)
            batches = iter(loader)
            return next(batches)

    t0 = time.perf_counter()
    for i in range(args.steps):
        # prefetched host batch (C-path gather + MLM mask); NSP labels
        # are synthetic — the corpus has no sentence-pair structure
        ids_np, labels_np = next_batch()
        b = (jnp.asarray(ids_np), jnp.asarray(labels_np),
             jnp.asarray(nsp_rng.randint(0, 2, (args.batch_size,))),
             jnp.ones((args.batch_size, args.seq), jnp.int32))
        prev = scaler_state
        params, opt_state, scaler_state, loss = step_fn(
            params, opt_state, scaler_state, *b)
        handle.scalers[0].host_overflow_report(prev, scaler_state)
        if i == 0:
            jax.block_until_ready(loss)
            t0 = time.perf_counter()  # exclude compile
            print(f"step 0 loss {float(loss):.4f} (compiled)")
        elif i == args.steps - 1 or i % 5 == 0:
            print(f"step {i} loss {float(loss):.4f} "
                  f"scale {float(scaler_state.loss_scale):.0f}")
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    steps_timed = max(args.steps - 1, 1)
    sps = args.batch_size * steps_timed / dt
    print(f"{steps_timed} steps in {dt:.2f}s = "
          f"{1000 * dt / steps_timed:.1f} ms/step, {sps:.1f} samples/s")


if __name__ == "__main__":
    main()
