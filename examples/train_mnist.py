"""MNIST-style MLP training through the full amp surface.

The rebuild's analog of the reference's runnable example tier
(``examples/imagenet/main_amp.py`` / ``examples/simple``, SURVEY.md §1)
and the BASELINE configs[0] smoke: a 2-layer MLP under
``amp.initialize`` at any opt level, with the dynamic loss scaler
visibly backing off (the contractual "Gradient overflow." line) when an
overflow is injected.

The sandbox has no network access, so the dataset is synthetic
MNIST-shaped data (class-dependent Gaussian blobs, 784 features, 10
classes) — the training dynamics, amp data flow, and observability are
the point, not digit accuracy.

Run::

    python examples/train_mnist.py --opt-level O1
    python examples/train_mnist.py --opt-level O2 --steps 200
    python examples/train_mnist.py --opt-level O1 --inject-inf-at -1  # clean

Works on CPU and on a TPU chip unchanged.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.mlp import MLP
from apex_tpu.optimizers import FusedAdam
from apex_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


def synthetic_mnist(n: int, seed: int = 0):
    """Class-separable 784-d blobs standing in for MNIST."""
    rng = np.random.RandomState(seed)
    centers = rng.randn(10, 784).astype("float32") * 0.5
    labels = rng.randint(0, 10, n)
    images = centers[labels] + rng.randn(n, 784).astype("float32")
    return images, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--opt-level", default="O1",
                    choices=["O0", "O1", "O2", "O3"])
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--loss-scale", default=None,
                    help='"dynamic" (default per opt level) or a float')
    ap.add_argument("--inject-inf-at", type=int, default=10,
                    help="poison this step's batch with inf to demo the "
                         "scaler backoff; -1 disables")
    ap.add_argument("--ckpt-dir", default=None,
                    help="save a checkpoint at the end / resume from it")
    args = ap.parse_args()

    print(f"backend={jax.default_backend()} opt_level={args.opt_level}")

    model = MLP((784, 256, 10), activation="relu")
    images, labels = synthetic_mnist(4096)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 784)))

    loss_scale = args.loss_scale
    if loss_scale is not None and loss_scale != "dynamic":
        loss_scale = float(loss_scale)
    params, optimizer, handle = amp.initialize(
        params, FusedAdam(lr=args.lr), opt_level=args.opt_level,
        loss_scale=loss_scale)

    opt_state = optimizer.init(params)
    scaler_state = handle.init_state()
    start_step = 0

    if args.ckpt_dir:
        try:
            restored = load_checkpoint(args.ckpt_dir, template=dict(
                params=params, opt_state=opt_state,
                scaler_state=scaler_state))
            params = restored["params"]
            opt_state = restored["opt_state"]
            scaler_state = restored["scaler_state"]
            start_step = restored["_step"]
            print(f"resumed from step {start_step}")
        except FileNotFoundError:
            pass

    compute_dtype = (handle.properties.cast_model_type
                     or handle.properties.compute_dtype or jnp.float32)

    @jax.jit
    def train_step(params, opt_state, scaler_state, x, y):
        def loss_fn(p):
            logits = model.apply(p, x.astype(compute_dtype))
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

        vg = handle.value_and_grad(loss_fn, scaler_state)
        (loss, found_inf), grads = vg(params)
        new_params, new_opt_state = optimizer.step(
            grads, opt_state, params, skip_if=found_inf)
        new_scaler_state = handle.update_scale(scaler_state, found_inf)
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                             for g in jax.tree.leaves(grads)))
        metrics = handle.scalers[0].metrics(new_scaler_state,
                                            grad_norm=gnorm, loss=loss)
        return new_params, new_opt_state, new_scaler_state, metrics

    nbatches = len(images) // args.batch_size
    metrics = None
    for step in range(start_step, args.steps):
        i = step % nbatches
        x = jnp.asarray(images[i * args.batch_size:(i + 1) * args.batch_size])
        y = jnp.asarray(labels[i * args.batch_size:(i + 1) * args.batch_size])
        if step == args.inject_inf_at:
            x = x.at[0, 0].set(jnp.inf)  # demo: scaler backoff + skip

        prev_scaler_state = scaler_state
        params, opt_state, scaler_state, metrics = train_step(
            params, opt_state, scaler_state, x, y)
        # contractual overflow line, printed host-side (works on runtimes
        # without host callbacks, e.g. the axon TPU plugin)
        handle.scalers[0].host_overflow_report(prev_scaler_state,
                                               scaler_state)
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step:4d} loss {float(metrics['loss']):.4f} "
                  f"scale {float(metrics['loss_scale']):.0f} "
                  f"grad_norm {float(metrics['grad_norm']):.3f} "
                  f"skipped {int(metrics['steps_skipped'])}")

    if args.ckpt_dir:
        path = save_checkpoint(args.ckpt_dir, args.steps, params=params,
                               opt_state=opt_state,
                               scaler_state=scaler_state)
        print(f"checkpoint saved: {path}")

    if metrics is None:  # resumed at or past --steps: nothing to do
        print(f"already trained to step {start_step}")
        return None
    final_loss = float(metrics["loss"])
    print(f"final loss {final_loss:.4f}")
    return final_loss


if __name__ == "__main__":
    main()
