"""Long-context training with ring attention (context parallelism).

Demonstrates the long-context story end to end: a small causal
transformer whose attention runs as :func:`apex_tpu.ops.ring_attention`
over a context-parallel mesh axis — each device holds S/cp tokens and
only ever materializes one (S/cp)-sized key/value block, so sequence
length scales linearly with the ring size. On a host with no
accelerator this runs the same code over 8 simulated devices
(cp=8); on a single TPU chip it runs cp=1 with the compiled Pallas
flash kernel at sequence lengths where materializing the (S, S) score
matrix would already cost gigabytes.

Run::

    python examples/train_long_context.py --seq 4096 --steps 10
    # CPU 8-device ring:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_long_context.py --seq 1024 --steps 5
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.ring_attention import ring_attention


def build_model(vocab, hidden, heads, axis):
    """Returns (init_params, loss_fn(params, ids_local)) — a 2-block
    causal LM over the sequence shard (functional, no flax, to keep the
    ring data flow visible)."""
    hd = hidden // heads

    def init_params(key):
        ks = jax.random.split(key, 8)
        s = 0.02
        return {
            "embed": jax.random.normal(ks[0], (vocab, hidden)) * s,
            "qkv0": jax.random.normal(ks[1], (hidden, 3 * hidden)) * s,
            "out0": jax.random.normal(ks[2], (hidden, hidden)) * s,
            "mlp0a": jax.random.normal(ks[3], (hidden, 4 * hidden)) * s,
            "mlp0b": jax.random.normal(ks[4], (4 * hidden, hidden)) * s,
            "qkv1": jax.random.normal(ks[5], (hidden, 3 * hidden)) * s,
            "out1": jax.random.normal(ks[6], (hidden, hidden)) * s,
            "unembed": jax.random.normal(ks[7], (hidden, vocab)) * s,
        }

    def block(x, qkv_w, out_w, drop_seed):
        B, S_local, _ = x.shape
        qkv = x @ qkv_w
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads_of(t):
            return t.reshape(B, S_local, heads, hd).transpose(0, 2, 1, 3)

        # TRUE training config: attention-probability dropout 0.1 fused
        # into the per-block flash kernels (round 4 — the ring derives
        # per-(q-block, kv-block) seeds from drop_seed internally, so
        # the lse merge stays exact and backward replays the masks)
        ctx = ring_attention(heads_of(q), heads_of(k), heads_of(v),
                             None, True, 1.0 / np.sqrt(hd), axis_name=axis,
                             dropout_rate=0.1, dropout_seed=drop_seed)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S_local, -1)
        return x + ctx @ out_w

    def loss_fn(params, ids, step_idx):
        x = params["embed"][ids]                     # (B, S_local, H)
        x = block(x, params["qkv0"], params["out0"], 2 * step_idx)
        x = x + jax.nn.gelu(x @ params["mlp0a"]) @ params["mlp0b"]
        x = block(x, params["qkv1"], params["out1"], 2 * step_idx + 1)
        logits = x @ params["unembed"]
        # next-token prediction within the shard (boundary token dropped
        # for simplicity; a production loader overlaps shards by 1)
        lse = jax.nn.logsumexp(logits[:, :-1].astype(jnp.float32), axis=-1)
        picked = jnp.take_along_axis(
            logits[:, :-1].astype(jnp.float32),
            ids[:, 1:, None], axis=-1)[..., 0]
        local = jnp.mean(lse - picked)
        return jax.lax.pmean(local, axis)

    return init_params, loss_fn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=4096, help="GLOBAL length")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=1)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--vocab", type=int, default=1024)
    ap.add_argument("--lr", type=float, default=3e-3)
    args = ap.parse_args()

    cp = jax.device_count()
    if args.seq % cp:
        raise SystemExit(f"--seq must be divisible by device count {cp}")
    mesh = jax.make_mesh((cp,), ("context",))
    print(f"backend={jax.default_backend()} ring size cp={cp} "
          f"global seq={args.seq} ({args.seq // cp}/device)")

    from apex_tpu.optimizers import FusedAdam

    init_params, loss_fn = build_model(args.vocab, args.hidden, 4, "context")
    params = init_params(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=args.lr)
    opt_state = opt.init(params)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, args.vocab,
                                  (args.batch_size, args.seq)))

    def step(params, opt_state, ids_local, step_idx):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids_local,
                                                  step_idx)
        # grads of replicated params are already psummed by shard_map AD
        params, opt_state = opt.step(grads, opt_state, params)
        return params, opt_state, loss

    stepped = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P(), P(None, "context"), P()),
        out_specs=(P(), P(), P())))

    t0 = time.perf_counter()
    for i in range(args.steps):
        params, opt_state, loss = stepped(params, opt_state, ids,
                                          jnp.int32(i))
        if i == 0:
            jax.block_until_ready(loss)
            t0 = time.perf_counter()
            print(f"step 0 loss {float(loss):.4f} (compiled)")
        elif i % 3 == 0 or i == args.steps - 1:
            print(f"step {i} loss {float(loss):.4f}")
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / max(args.steps - 1, 1)
    toks = args.batch_size * args.seq / dt
    print(f"{dt * 1e3:.1f} ms/step = {toks:.0f} tokens/s "
          f"(S={args.seq}, never materializing the (S,S) score matrix)")


if __name__ == "__main__":
    main()
