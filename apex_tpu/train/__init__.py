"""apex_tpu.train — the composed training step (docs/training.md).

The training-side dual of ``apex_tpu.serving``: where the serving
engine fuses K decode iterations into one dispatch with deferred host
sync, :func:`build_train_step` fuses the whole global optimizer step —
forward, backward, loss-scale unscale + in-graph overflow skip,
scanned gradient accumulation, one post-scan DDP allreduce, fused
optimizer update — into ONE donated-buffer dispatch, and
:class:`TrainLoop` defers every metrics fetch behind the next
dispatch.

``build_reference_loop`` builds the hand-wired per-microbatch dispatch
loop with bit-identical math — the certification baseline used by
tests and ``bench_train_step``.
"""

from apex_tpu.train.loop import (  # noqa: F401
    NonFiniteLossError,
    TrainLoop,
    WatchdogConfig,
)
from apex_tpu.train.step import (  # noqa: F401
    ReferenceLoop,
    TrainState,
    TrainStep,
    build_reference_loop,
    build_train_step,
)
