"""Deferred-metrics training loop driver, with the robustness layer.

The serving engine's deferred sync (PR 3) restated for training: the
host must never stand between two device dispatches. A loop that reads
``loss`` right after ``step()`` serializes host and device — every step
pays a full dispatch + fetch round trip. :class:`TrainLoop` instead
keeps step ``t``'s metrics as unfetched device scalars, dispatches step
``t+1``, and only THEN fetches ``t``'s values: the fetch overlaps the
in-flight step, so the device queue never drains.

Contract (docs/training.md): ``loop.step(batch)`` returns the metrics
of the PREVIOUS step (``None`` on the first call); ``loop.drain()``
returns the final pending metrics after the last step. Metrics arrive
as host scalars (plain Python ``float``/``int``/``bool``), with any
``aux`` pytree left as numpy arrays.

Robustness (docs/robustness.md) — a long pretraining run survives the
three ways a step dies:

- **Transient dispatch failure**: the step call is retried up to
  ``max_retries`` times with exponential backoff. Sound when the
  failure precedes buffer consumption (the fault harness fires before
  the launch; a compile-service drop raises at dispatch) — a real
  mid-flight device failure with donated buffers is NOT retryable, and
  the loop re-raises for checkpoint recovery instead.
- **Non-finite loss**: amp's in-graph overflow skip already protects
  the params inside the graph, but it would happily skip *forever* on
  persistently-poisoned data. The host-side watchdog escalates on
  CONSECUTIVE non-finite losses: tolerate (skip) → halve the loss
  scale (rescale) → raise :class:`NonFiniteLossError` (halt). Because
  metrics are deferred, the watchdog sees step ``t`` after dispatching
  ``t+1``; its actions land one step late — the price of never
  blocking the device.
- **Process death**: periodic checkpoints of the (host-copied, so
  donation-safe) :class:`TrainState` via
  :mod:`apex_tpu.utils.checkpoint`; ``load_train_state`` +
  a fresh loop resumes bit-identically to the uninterrupted run
  (certified in tests/test_faults.py).
"""

from __future__ import annotations

import math
import dataclasses
from typing import Any, Dict, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.utils.faults import guarded_call


def _to_host(metrics) -> Dict[str, Any]:
    """One host fetch of a metrics pytree, scalars unwrapped to Python."""
    fetched = jax.device_get(metrics)

    def unwrap(x):
        arr = np.asarray(x)
        return arr.item() if arr.ndim == 0 else arr

    return jax.tree.map(unwrap, fetched)


class NonFiniteLossError(RuntimeError):
    """The watchdog's halt rung: the loss stayed non-finite through the
    skip and rescale rungs — training is wedged, and silently skipping
    every step forever would burn the cluster while the curves flatline.
    Carries the offending host ``metrics`` and the loop's ``stats()``."""

    def __init__(self, message: str, metrics: Dict[str, Any],
                 stats: Dict[str, Any]):
        super().__init__(f"{message} (metrics: {metrics})")
        self.metrics = metrics
        self.loop_stats = stats


@dataclasses.dataclass(frozen=True)
class WatchdogConfig:
    """The non-finite-loss escalation ladder, rung widths in
    CONSECUTIVE non-finite steps (a single finite loss resets the
    climb): the first ``skip_steps`` are tolerated (amp's in-graph skip
    already protected the params — this rung just counts), the next
    ``rescale_steps`` each halve the loss scale from the host (floored
    at ``min_scale``; a scale the in-graph backoff may be too slow to
    reach while every step overflows), and anything past that raises
    :class:`NonFiniteLossError`. Distinct from the scaler's own
    in-graph backoff: the watchdog is host policy about *giving up*,
    not graph arithmetic about the next scale."""

    skip_steps: int = 3
    rescale_steps: int = 3
    min_scale: float = 1.0
    loss_key: str = "loss"

    def __post_init__(self):
        if self.skip_steps < 0 or self.rescale_steps < 0:
            raise ValueError("watchdog rung widths must be >= 0")


class TrainLoop:
    """Drive a :class:`~apex_tpu.train.TrainStep` with deferred metric
    fetches.

    The loop OWNS the evolving :class:`TrainState`: with a donating step
    the previous state's buffers are consumed by each dispatch, so
    callers must not hold references to past states (see the donation
    caveats in docs/training.md). Read ``loop.state`` only between
    steps, and only the latest value.

    Keyword-only robustness knobs (all default off / inert):
    ``faults`` (a :class:`~apex_tpu.utils.faults.FaultPlan`, fired at
    site ``"train_step"`` before each dispatch), ``max_retries`` /
    ``retry_backoff_s`` (transient-failure retry), ``watchdog`` (a
    :class:`WatchdogConfig`), ``checkpoint_dir`` + ``checkpoint_every``
    (periodic :func:`apex_tpu.utils.checkpoint.save_train_state` every
    N completed steps — each save host-syncs the full state, so pick N
    against your step time), and ``obs`` (an
    :class:`~apex_tpu.observability.Observability` —
    docs/observability.md): a per-step host-span histogram, step/
    retry/non-finite counters with Prometheus exposition via
    ``stats(deep=True)``, and watchdog/checkpoint events into the
    flight recorder. Observation-only, like the engine's: nothing the
    loop computes ever reads observer state.
    """

    def __init__(self, train_step, state, *, faults=None,
                 max_retries: int = 2, retry_backoff_s: float = 0.0,
                 watchdog: Optional[WatchdogConfig] = None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 0, obs=None):
        self._train_step = train_step
        self.state = state
        self._obs = obs
        if obs is not None:
            obs.bind_train()
        self._pending = None  # last step's unfetched device metrics
        self._faults = faults
        self._max_retries = int(max_retries)
        self._retry_backoff_s = float(retry_backoff_s)
        self._watchdog = watchdog
        self._ckpt_dir = checkpoint_dir
        self._ckpt_every = int(checkpoint_every)
        self._steps_dispatched = 0
        self._retries = 0
        self._nonfinite_run = 0        # consecutive non-finite losses
        self._watchdog_trips = 0       # total non-finite losses observed
        self._watchdog_skips = 0
        self._watchdog_rescales = 0
        self._watchdog_halts = 0
        self._checkpoints_saved = 0
        self._last_checkpoint_step: Optional[int] = None
        # metrics collected by the current/last run(), INCLUDING the
        # finally-drained last step when run() unwinds on an exception
        self.last_run_metrics: List[Dict[str, Any]] = []

    # -- the dispatch path -------------------------------------------------

    def step(self, batch) -> Optional[Dict[str, Any]]:
        """Dispatch one global step; return the PREVIOUS step's metrics
        (fetched only now, while this step runs) — ``None`` on the
        first call. Transient dispatch failures retry with bounded
        backoff; exhaustion raises
        :class:`~apex_tpu.utils.faults.DispatchFailedError`. The
        watchdog inspects every fetched metrics dict and may raise
        :class:`NonFiniteLossError` from here (halt rung)."""
        obs = self._obs
        t0 = obs.now() if obs is not None else 0.0

        def count(attempt):
            self._retries += 1
            if obs is not None:
                obs.record("fault_retry", site="train_step",
                           attempt=attempt)
                obs.inc("retries")

        (new_state, metrics), nan_hit = guarded_call(
            self._train_step, self.state, batch, plan=self._faults,
            site="train_step", retries=self._max_retries,
            backoff_s=self._retry_backoff_s, on_retry=count)
        self.state = new_state
        self._steps_dispatched += 1
        if nan_hit:
            # the injected silent failure: the step ran, its loss is
            # garbage — exactly what the watchdog exists to catch
            metrics = dict(metrics)
            metrics[self._watchdog.loss_key if self._watchdog is not None
                    else "loss"] = float("nan")
        prev, self._pending = self._pending, metrics
        out = None if prev is None else _to_host(prev)
        if obs is not None:
            # the deferred-metrics host span: this step's dispatch plus
            # the PREVIOUS step's fetch — exactly what the loop's
            # overlap design is supposed to keep short
            dt = obs.now() - t0
            obs.inc("steps")
            obs.observe("step", dt)
            mesh_shape = getattr(self._train_step, "mesh_shape", None)
            if mesh_shape is not None:
                obs.record("train_step", step=self._steps_dispatched,
                           host_span_s=dt, mesh=list(mesh_shape))
            else:
                obs.record("train_step", step=self._steps_dispatched,
                           host_span_s=dt)
        if out is not None:
            self._observe(out, raise_on_halt=True)
        self._maybe_checkpoint()
        return out

    def drain(self, raise_on_halt: bool = False) -> Optional[Dict[str, Any]]:
        """Fetch the final pending metrics (call after the last
        :meth:`step`); ``None`` if nothing is pending. Also the
        loop-end synchronization barrier: once it returns, every
        dispatched step has executed. By default the watchdog observes
        (counts) the drained metrics but never raises from here —
        drain runs in ``finally`` blocks, where a fresh raise would
        mask the original failure. Pass ``raise_on_halt=True`` when
        nothing is unwinding (the completed-run drain), so a halt
        threshold first crossed by the LAST step's metrics still
        halts instead of returning a wedged run as success."""
        prev, self._pending = self._pending, None
        out = None if prev is None else _to_host(prev)
        if out is not None:
            self._observe(out, raise_on_halt=raise_on_halt)
        return out

    def run(self, batches: Iterable) -> List[Dict[str, Any]]:
        """Feed every batch, deferred throughout; returns all metrics in
        step order (the last entry fetched by the closing drain).

        The in-flight dispatch is drained in a ``finally``: an
        exception mid-iteration (watchdog halt, exhausted retries, a
        poisoned fetch) no longer silently drops the last completed
        step's metrics — everything fetched so far, including that
        final drain, stays readable on ``loop.last_run_metrics``."""
        out: List[Dict[str, Any]] = []
        self.last_run_metrics = out
        completed = False
        try:
            for batch in batches:
                m = self.step(batch)
                if m is not None:
                    out.append(m)
            completed = True
        finally:
            if completed:
                # nothing is unwinding here, so the watchdog may halt
                m = self.drain(raise_on_halt=True)
            else:
                # already unwinding: the drain must not mask the
                # original exception, so its own failure is dropped
                try:
                    m = self.drain()
                except Exception:
                    m = None
            if m is not None:
                out.append(m)
        return out

    # -- the non-finite-loss watchdog --------------------------------------

    def _observe(self, metrics: Dict[str, Any], raise_on_halt: bool) -> None:
        wd = self._watchdog
        if wd is None:
            return
        loss = metrics.get(wd.loss_key)
        if loss is None:
            return
        if math.isfinite(float(loss)):
            self._nonfinite_run = 0
            return
        self._nonfinite_run += 1
        self._watchdog_trips += 1
        obs = self._obs
        if obs is not None:
            obs.inc("nonfinite")
        run = self._nonfinite_run
        if run <= wd.skip_steps:
            self._watchdog_skips += 1
            if obs is not None:
                obs.record("watchdog", action="skip", run=run)
        elif run <= wd.skip_steps + wd.rescale_steps:
            self._watchdog_rescales += 1
            if obs is not None:
                obs.record("watchdog", action="rescale", run=run)
            self._rescale(wd)
        elif raise_on_halt:
            # counted only when actually raised: a drain (already
            # unwinding) may observe one more halt-level loss, which is
            # the same failure, not a second halt
            self._watchdog_halts += 1
            if obs is not None:
                obs.record("watchdog", action="halt", run=run)
                obs.incident("watchdog_halt", run=run)
            raise NonFiniteLossError(
                f"loss non-finite for {run} consecutive steps "
                f"(through {wd.skip_steps} skips and "
                f"{wd.rescale_steps} rescales)", metrics, self.stats())

    def _rescale(self, wd: WatchdogConfig) -> None:
        """The ladder's middle rung: halve the loss scale FROM THE HOST
        (one scalar fetch + re-upload — rare by construction). The
        scaler's own in-graph backoff does this too, but only per
        overflow step and only down its own schedule; the watchdog's
        version is the blunt recovery lever for runs where every step
        overflows and waiting for the in-graph walk means burning the
        job."""
        sst = self.state.scaler_state
        cur = float(jax.device_get(sst.loss_scale))
        new = max(cur / 2.0, wd.min_scale)
        fresh = jnp.asarray(new, jnp.float32)
        # a mesh-sharded state (the GSPMD train step) commits every
        # leaf; the replacement scalar must land on the same sharding
        # or the next dispatch retraces on the one uncommitted leaf
        sharding = getattr(sst.loss_scale, "sharding", None)
        if getattr(sharding, "mesh", None) is not None:
            fresh = jax.device_put(fresh, sharding)
        self.state = self.state._replace(
            scaler_state=sst._replace(loss_scale=fresh))

    # -- checkpoint / resume ----------------------------------------------

    def save_checkpoint(self) -> str:
        """Host-copy the current :class:`TrainState` and write it under
        ``checkpoint_dir`` (step number read from ``state.step``).
        Forces a device sync of the whole state — donation-safe, since
        the copy owns its buffers. Returns the checkpoint path."""
        from apex_tpu.utils.checkpoint import save_train_state

        if self._ckpt_dir is None:
            raise ValueError("TrainLoop was built without checkpoint_dir")
        path = save_train_state(self._ckpt_dir, self.state)
        self._checkpoints_saved += 1
        self._last_checkpoint_step = int(
            np.asarray(jax.device_get(self.state.step)))
        if self._obs is not None:
            self._obs.inc("checkpoints")
            self._obs.record("checkpoint",
                             step=self._last_checkpoint_step, path=path)
        return path

    def _maybe_checkpoint(self) -> None:
        if (self._ckpt_dir is None or self._ckpt_every <= 0
                or self._steps_dispatched % self._ckpt_every):
            return
        self.save_checkpoint()

    # -- observability -----------------------------------------------------

    def stats(self, deep: bool = False) -> Dict[str, Any]:
        """Failure-path counters (docs/robustness.md): everything the
        chaos suite asserts nonzero rides here. ``deep=True`` merges
        the attached observer's section (metric values, recorder
        depth) under ``"observability"`` — the same contract as
        ``InferenceEngine.stats(deep=True)``
        (docs/observability.md)."""
        out = {
            "steps_dispatched": self._steps_dispatched,
            "dispatch_retries": self._retries,
            "watchdog_nonfinite": self._watchdog_trips,
            "watchdog_skips": self._watchdog_skips,
            "watchdog_rescales": self._watchdog_rescales,
            "watchdog_halts": self._watchdog_halts,
            "checkpoints_saved": self._checkpoints_saved,
            "last_checkpoint_step": self._last_checkpoint_step,
        }
        if deep and self._obs is not None:
            out["observability"] = self._obs.deep_stats()
        return out
