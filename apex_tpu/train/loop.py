"""Deferred-metrics training loop driver.

The serving engine's deferred sync (PR 3) restated for training: the
host must never stand between two device dispatches. A loop that reads
``loss`` right after ``step()`` serializes host and device — every step
pays a full dispatch + fetch round trip. :class:`TrainLoop` instead
keeps step ``t``'s metrics as unfetched device scalars, dispatches step
``t+1``, and only THEN fetches ``t``'s values: the fetch overlaps the
in-flight step, so the device queue never drains.

Contract (docs/training.md): ``loop.step(batch)`` returns the metrics
of the PREVIOUS step (``None`` on the first call); ``loop.drain()``
returns the final pending metrics after the last step. Metrics arrive
as host scalars (plain Python ``float``/``int``/``bool``), with any
``aux`` pytree left as numpy arrays.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

import jax
import numpy as np


def _to_host(metrics) -> Dict[str, Any]:
    """One host fetch of a metrics pytree, scalars unwrapped to Python."""
    fetched = jax.device_get(metrics)

    def unwrap(x):
        arr = np.asarray(x)
        return arr.item() if arr.ndim == 0 else arr

    return jax.tree.map(unwrap, fetched)


class TrainLoop:
    """Drive a :class:`~apex_tpu.train.TrainStep` with deferred metric
    fetches.

    The loop OWNS the evolving :class:`TrainState`: with a donating step
    the previous state's buffers are consumed by each dispatch, so
    callers must not hold references to past states (see the donation
    caveats in docs/training.md). Read ``loop.state`` only between
    steps, and only the latest value.
    """

    def __init__(self, train_step, state):
        self._train_step = train_step
        self.state = state
        self._pending = None  # last step's unfetched device metrics

    def step(self, batch) -> Optional[Dict[str, Any]]:
        """Dispatch one global step; return the PREVIOUS step's metrics
        (fetched only now, while this step runs) — ``None`` on the
        first call."""
        self.state, metrics = self._train_step(self.state, batch)
        prev, self._pending = self._pending, metrics
        return None if prev is None else _to_host(prev)

    def drain(self) -> Optional[Dict[str, Any]]:
        """Fetch the final pending metrics (call after the last
        :meth:`step`); ``None`` if nothing is pending. Also the
        loop-end synchronization barrier: once it returns, every
        dispatched step has executed."""
        prev, self._pending = self._pending, None
        return None if prev is None else _to_host(prev)

    def run(self, batches: Iterable) -> List[Dict[str, Any]]:
        """Feed every batch, deferred throughout; returns all metrics in
        step order (the last entry fetched by the closing drain)."""
        out = []
        for batch in batches:
            m = self.step(batch)
            if m is not None:
                out.append(m)
        m = self.drain()
        if m is not None:
            out.append(m)
        return out
