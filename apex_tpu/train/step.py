"""The fused train step: one donated dispatch per global optimizer step.

apex exists to make the training step one fused device pass — amp,
``multi_tensor_apply`` optimizers, and bucketed-allreduce DDP are all
pieces of that loop — but composing them by hand leaves the *step
structure* on the host: one dispatch per microbatch, a separate
optimizer dispatch, a host fetch of the loss every step, and a
transient second copy of params + moments because nothing is donated.
The serving engine already proved this stack is dispatch/host-sync
bound (fusing K decode steps per dispatch took CPU decode 880 -> 2835
tok/s); this module applies the same physics to training:

- **One dispatch per global step.** Forward, backward, loss-scale
  unscale + in-graph overflow skip, gradient accumulation, DDP
  allreduce, and the fused optimizer update compile into a single
  jitted program.
- **Scanned gradient accumulation.** The ``accum_steps`` microbatches
  run as a ``jax.lax.scan`` inside that program. Gradients accumulate
  on-device in fp32; the DDP collective runs ONCE after the scan
  (``DistributedDataParallel.allreduce_accumulated``), not once per
  microbatch.
- **Donated buffers.** The :class:`TrainState` argument is donated, so
  params, optimizer moments, and scaler state alias in place — no
  transient second copy of BERT-large params + moments. The compiled
  program's ``input_output_alias`` table is auditable via
  :meth:`TrainStep.alias_stats`
  (:func:`apex_tpu.utils.hlo_audit.input_output_alias_stats`), because
  XLA drops donation silently when a layout mismatches.
- **Deferred metrics.** Step metrics (loss, scale, skip counters) come
  back as device scalars; :class:`apex_tpu.train.TrainLoop` fetches
  step ``t-1``'s metrics after dispatching step ``t`` — the training
  analog of the serving engine's deferred sync — so the host never
  blocks the device.

Certification: :func:`build_reference_loop` builds the hand-wired
per-microbatch dispatch loop (one jitted program per microbatch plus an
apply program) from the SAME configuration with bit-identical math in
the same order; tests and ``bench_train_step`` certify the fused scan
against it the way the serving bench certifies cross-K decode.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp.handle import AmpHandle
from apex_tpu.amp.scaler import LossScaler, ScalerState
from apex_tpu.utils.collectives import compat_shard_map
from apex_tpu.utils.pytree import all_finite, global_norm

try:  # jax.sharding is stable across the vintages we support
    from jax.sharding import PartitionSpec as _P
except ImportError:  # pragma: no cover
    _P = None


class TrainState(NamedTuple):
    """The donated carry of the fused step: everything that evolves.

    Treat a ``TrainState`` you passed into a donating step as CONSUMED —
    its buffers now back the returned state. Reading a donated array
    raises; keep only the returned state (see docs/training.md).
    """

    step: jnp.ndarray        # i32 — completed global optimizer steps
    params: Any
    opt_state: Any
    scaler_state: ScalerState


def _resolve_scaler(amp, loss_id: int):
    """(scaler, trace_wrapper) from an AmpHandle, a LossScaler, or None
    (None = static unity scale: unscale is exact, update only counts)."""
    if isinstance(amp, AmpHandle):
        return amp.scaler(loss_id), amp.traced
    if isinstance(amp, LossScaler):
        return amp, None
    if amp is None:
        return LossScaler(loss_scale=1.0), None
    raise TypeError(
        f"amp must be an AmpHandle, a LossScaler, or None; got {type(amp)}")


def _strip_leading_axis(spec):
    """Drop the leading (accumulation-axis) entry from a PartitionSpec
    or a pytree of them — the reference loop feeds one microbatch at a
    time, so its per-dispatch specs lose the accum axis the fused
    scan's specs carry."""
    if _P is not None and isinstance(spec, _P):
        return _P(*tuple(spec)[1:])
    return jax.tree.map(_strip_leading_axis, spec,
                        is_leaf=lambda s: isinstance(s, _P))


def _check_batch(batch, accum_steps: int):
    leaves = jax.tree.leaves(batch)
    if not leaves:
        raise ValueError("batch has no leaves")
    for leaf in leaves:
        shape = jnp.shape(leaf)
        if not shape or shape[0] != accum_steps:
            raise ValueError(
                f"every batch leaf needs a leading microbatch axis of "
                f"length accum_steps={accum_steps}; got shape {shape}. "
                f"Reshape [accum*B, ...] data to [accum, B, ...].")


def _is_flat_optimizer(optimizer) -> bool:
    from apex_tpu.contrib.optimizers.distributed_fused_adam import (
        _DistributedFlatOptimizer,
    )

    return isinstance(optimizer, _DistributedFlatOptimizer)


class _GspmdPlan:
    """The sharded train step's layout plan: one object owning every
    NamedSharding decision of the GSPMD path (``build_train_step`` with
    ``mesh=`` and no ``ddp=``) —

    - **params** follow ``pspec_fn(path)`` (default: the Megatron
      decomposition, :func:`apex_tpu.models.gpt.gpt_param_pspec`) —
      tensor-parallel activations fall out of GSPMD propagation;
    - **optimizer state**: a ZeRO flat optimizer's lane-shaped stream
      shards ``P("batch", None)`` (each rank owns its flat row block);
      per-leaf moments mirror their parameter's spec (``pspec_fn`` is
      applied by trailing path, which moment subtrees preserve);
    - **batch** leaves shard ``batch_spec`` (default ``P(None,
      "batch")``: accumulation axis unsharded, global batch split over
      the batch axis — the data-parallel leg, reductions inserted by
      the partitioner from the global-mean loss);
    - **scalars** (step counter, scaler state, metrics) replicate.

    The plan is applied twice per object: ``commit_state`` device_puts
    the initial state (committed inputs = stable jit cache keys), and
    ``constrain_state`` pins the OUTPUT layouts inside the jitted
    program — without the output pin GSPMD may hand back a
    differently-laid-out tree whose next dispatch recompiles, the same
    one-program contract the serving mesh pins with out_shardings.
    """

    def __init__(self, mesh, pspec_fn, batch_spec, zero: bool):
        from jax.sharding import NamedSharding

        self.mesh = mesh
        self.pspec_fn = pspec_fn
        self.batch_spec = batch_spec
        self.zero = zero
        self.rep = NamedSharding(mesh, _P())
        self.zspec = self._named(_P("batch", None))

    def _canon(self, spec):
        """Canonicalize a PartitionSpec the way GSPMD spells output
        shardings: drop axis names of mesh size 1, then strip trailing
        ``None`` entries (``P('model', None)`` → ``P('model')``,
        ``P(None, 'model')`` on a model=1 mesh → ``P()``). Committing
        inputs with the exact output spelling is what pins the jit
        cache at one entry — a semantically-equal-but-differently-
        spelled sharding is a cache MISS, and the second dispatch
        silently retraces."""
        shape = dict(self.mesh.shape)

        def live(entry):
            if entry is None:
                return None
            if isinstance(entry, (tuple, list)):
                kept = tuple(a for a in entry if shape.get(a, 1) > 1)
                return kept if kept else None
            return entry if shape.get(entry, 1) > 1 else None

        entries = [live(e) for e in tuple(spec)]
        while entries and entries[-1] is None:
            entries.pop()
        return _P(*entries)

    def _named(self, spec):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, self._canon(spec))

    # -- shardings ------------------------------------------------------

    def param_shardings(self, params):
        return jax.tree_util.tree_map_with_path(
            lambda path, x: self._named(self.pspec_fn(path)), params)

    def opt_shardings(self, opt_state):
        if self.zero:
            # ShardedOptState: scalar step + three lane-shaped streams
            return type(opt_state)(
                step=self.rep, exp_avg=self.zspec,
                exp_avg_sq=self.zspec, master=self.zspec)
        # per-leaf moments mirror params: the trailing (module, leaf)
        # path names survive the NamedTuple wrapper, so pspec_fn applies
        return jax.tree_util.tree_map_with_path(
            lambda path, x: (self._named(self.pspec_fn(path))
                             if jnp.ndim(x) else self.rep),
            opt_state)

    def batch_shardings(self, batch):
        if isinstance(self.batch_spec, _P):
            specs = jax.tree.map(lambda x: self.batch_spec, batch)
        else:
            specs = self.batch_spec
        axis_sizes = dict(self.mesh.shape)

        def check(x, spec):
            shape = jnp.shape(x)
            for dim, names in enumerate(tuple(spec)):
                if names is None:
                    continue
                names = names if isinstance(names, tuple) else (names,)
                div = 1
                for n in names:
                    div *= axis_sizes[n]
                if dim >= len(shape) or shape[dim] % div:
                    raise ValueError(
                        f"mesh axis {names} (size {div}) must divide "
                        f"batch dim {dim} of leaf shape {shape} — pad "
                        f"the per-step batch to a multiple of the mesh "
                        f"batch axis or shrink the mesh")
            return self._named(spec)

        return jax.tree.map(check, batch, specs)

    # -- placement ------------------------------------------------------

    @staticmethod
    def _put(x, sharding):
        return (jax.device_put(x, sharding) if hasattr(x, "ndim")
                or not isinstance(x, int) else x)

    @staticmethod
    def _pin(x, sharding):
        return (jax.lax.with_sharding_constraint(x, sharding)
                if hasattr(x, "ndim") or not isinstance(x, int) else x)

    def _place_state(self, state: TrainState, put) -> TrainState:
        rep_tree = lambda tree: jax.tree.map(  # noqa: E731
            lambda x: put(x, self.rep), tree)
        return TrainState(
            step=put(state.step, self.rep),
            params=jax.tree.map(put, state.params,
                                self.param_shardings(state.params)),
            opt_state=jax.tree.map(put, state.opt_state,
                                   self.opt_shardings(state.opt_state)),
            scaler_state=rep_tree(state.scaler_state),
        )

    def commit_state(self, state: TrainState) -> TrainState:
        return self._place_state(state, self._put)

    def constrain_state(self, state: TrainState) -> TrainState:
        return self._place_state(state, self._pin)

    def commit_batch(self, batch):
        return jax.tree.map(jax.device_put, batch,
                            self.batch_shardings(batch))

    def constrain_metrics(self, metrics):
        return jax.tree.map(
            lambda x: self._pin(x, self.rep), metrics)


class _StepCore:
    """Shared math of the fused step and the reference loop — ONE
    definition so the certification compares program structure, never
    two transcriptions of the update rule."""

    def __init__(self, loss_fn, optimizer, scaler, trace_wrapper, ddp,
                 accum_steps, has_aux, lr_schedule, with_grad_norm,
                 loss_id):
        self.loss_fn = loss_fn if trace_wrapper is None else trace_wrapper(loss_fn)
        self.optimizer = optimizer
        self.scaler = scaler
        self.ddp = ddp
        self.accum_steps = int(accum_steps)
        self.has_aux = has_aux
        self.lr_schedule = lr_schedule
        self.with_grad_norm = with_grad_norm
        self.loss_id = loss_id
        # GSPMD hook (set by TrainStep on the mesh path): constrain the
        # fp32 grad accumulator to the PARAM pspecs at every boundary —
        # the scan carry, and the reduced grads entering the optimizer.
        # Left to propagation, the partitioner gives backward-pass grad
        # leaves layouts that mismatch the committed moment buffers, and
        # reconciles each elementwise Adam op with an all-to-all (and
        # reshards the carry every scan iteration). A no-op when unset
        # and at a (1, 1) mesh — the bit-identity certifications hold.
        self.acc_constraint = None
        if self.accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    # -- per-microbatch accumulation (identical in fused and reference) --

    def microbatch(self, params, sst: ScalerState, carry, mb):
        """Accumulate one microbatch's unscaled fp32 grads into carry.

        carry = (acc_f32_tree, loss_sum_f32, inf_any_bool[, aux_slot]).
        The scaled value_and_grad + unscale + finite check is exactly
        what a hand-wired loop calls per microbatch
        (:meth:`LossScaler.value_and_grad`) — the fused scan must not
        change a single op of it.
        """
        acc, loss_sum, inf_any = carry[:3]
        vg = self.scaler.value_and_grad(
            lambda p: self.loss_fn(p, mb), sst, has_aux=self.has_aux)
        if self.has_aux:
            (loss, found, aux), grads = vg(params)
        else:
            (loss, found), grads = vg(params)
            aux = None
        acc = jax.tree.map(lambda a, g: a + g.astype(jnp.float32),
                           acc, grads)
        loss_sum = loss_sum + loss.astype(jnp.float32)
        inf_any = jnp.logical_or(inf_any, found)
        return (acc, loss_sum, inf_any), aux

    def zero_carry(self, params):
        acc = jax.tree.map(lambda p: jnp.zeros(jnp.shape(p), jnp.float32),
                           params)
        if self.acc_constraint is not None:
            acc = self.acc_constraint(acc)
        return acc, jnp.zeros((), jnp.float32), jnp.zeros((), bool)

    # -- post-accumulation tail (identical in fused and reference) -------

    def reduce_grads(self, acc):
        """Average over microbatches, then the single post-scan
        synchronization (when DDP is configured)."""
        if self.ddp is not None:
            return self.ddp.allreduce_accumulated(acc, self.accum_steps)
        if self.accum_steps > 1:
            acc = jax.tree.map(
                lambda a: a / jnp.asarray(self.accum_steps, a.dtype), acc)
        if self.acc_constraint is not None:
            acc = self.acc_constraint(acc)
        return acc

    def apply(self, state: TrainState, acc, loss_sum, inf_any, aux=None):
        """Reduce, globalize the overflow flag, optimizer update, scaler
        update, metrics. Returns ``(new_state, metrics)``."""
        grads = self.reduce_grads(acc)
        # Globalize the skip decision: a non-finite grad on ANY device /
        # microbatch is already non-finite in the reduced tree (inf
        # survives both the fp32 accumulate and the psum), so this one
        # check makes every device skip in lockstep — per-device local
        # flags alone would let replicas diverge under DDP.
        found = jnp.logical_or(inf_any,
                               jnp.logical_not(all_finite(grads)))
        lr = (None if self.lr_schedule is None
              else self.lr_schedule(state.step))

        # The optimizer update runs as a real lax.cond branch on the
        # TRACED overflow flag, not a compute-both tree_select. Two
        # reasons. (1) Certification: a cond branch is its own HLO
        # computation, so XLA's fusion/FMA-contraction decisions inside
        # it cannot depend on the enclosing program — the fused step and
        # the reference apply dispatch compile the identical update
        # arithmetic identically (inlined, the p - lr*update chain
        # contracted differently between the two programs and drifted an
        # ulp by step 2). (2) Semantics: an overflow step now skips the
        # update work entirely, the in-graph form of apex's patched
        # optimizer.step() no-op.
        def _apply_branch(operands):
            g, ost, p = operands
            return self.optimizer.apply_gradients(g, ost, p,
                                                  skip_if=None, lr=lr)

        def _skip_branch(operands):
            _, ost, p = operands
            return p, ost

        new_params, new_opt = jax.lax.cond(
            found, _skip_branch, _apply_branch,
            (grads, state.opt_state, state.params))
        new_sst = self.scaler.update(state.scaler_state, found)
        loss = loss_sum / jnp.asarray(self.accum_steps, jnp.float32)
        if self.ddp is not None:
            loss = jax.lax.pmean(loss, self.ddp.axis_name)
        metrics = {
            "loss": loss,
            "loss_scale": state.scaler_state.loss_scale,  # scale USED
            "skipped": found,
            "steps_skipped": new_sst.steps_skipped,
            "step": state.step + 1,
        }
        if self.with_grad_norm:
            metrics["grad_norm"] = global_norm(grads)
        if aux is not None:
            if self.ddp is not None:
                # aux is device-varying (per-example values of THIS
                # device's shard); the metrics out_spec is replicated, so
                # without a gather one undefined device's slice would
                # silently survive. Gather to an explicit leading device
                # axis: [world, accum, ...local] — lossless and
                # shape-predictable for any user aux pytree.
                aux = jax.tree.map(
                    lambda a: jax.lax.all_gather(a, self.ddp.axis_name),
                    aux)
            metrics["aux"] = aux
        new_state = TrainState(
            step=state.step + 1,
            params=new_params,
            opt_state=new_opt,
            scaler_state=new_sst,
        )
        return new_state, metrics

    # -- the fused single-dispatch program -------------------------------

    def fused_step(self, state: TrainState, batch):
        params, sst = state.params, state.scaler_state

        def body(carry, mb):
            new_carry, aux = self.microbatch(params, sst, carry, mb)
            if self.acc_constraint is not None:
                acc_c, loss_c, inf_c = new_carry
                new_carry = (self.acc_constraint(acc_c), loss_c, inf_c)
            # Pin the reference loop's DISPATCH boundary: each hand-wired
            # microbatch ends a program, so nothing there cross-fuses the
            # backward into the next phase's arithmetic. When this scan
            # unrolls (accum_steps=1), XLA would fuse backward straight
            # into the optimizer update and shift the final params by an
            # ulp — breaking the fused-vs-loop bit-identity certification
            # for a "fusion" the baseline could never perform. The
            # barrier costs nothing at trip >= 2 (the scan boundary is
            # already a barrier) and keeps the certification honest.
            return jax.lax.optimization_barrier(new_carry), aux

        (acc, loss_sum, inf_any), aux = jax.lax.scan(
            body, self.zero_carry(params), batch)
        if not self.has_aux:
            aux = None
        return self.apply(state, acc, loss_sum, inf_any, aux=aux)


class TrainStep:
    """A compiled global train step; build with :func:`build_train_step`.

    ``step(state, batch) -> (new_state, metrics)`` where ``batch``
    leaves are shaped ``[accum_steps, per_step_batch, ...]`` and
    ``metrics`` are DEVICE scalars (fetch deferred — see
    :class:`apex_tpu.train.TrainLoop`). ``state`` is donated when
    ``donate=True`` (default): the passed-in state is consumed.
    """

    def __init__(self, core: _StepCore, donate: bool, mesh, batch_spec,
                 param_pspec=None, num_heads: Optional[int] = None):
        self._core = core
        self.donate = donate
        self.accum_steps = core.accum_steps
        self._plan: Optional[_GspmdPlan] = None
        self.mesh_shape: Optional[tuple] = None
        fn = core.fused_step
        if mesh is not None and core.ddp is None:
            # GSPMD single-dispatch path: ZeRO + tensor parallel via
            # sharding annotation on the serving mesh, no shard_map
            from apex_tpu.serving.mesh import MESH_AXES, validate_mesh_shape

            if tuple(mesh.axis_names) != MESH_AXES:
                raise ValueError(
                    f"mesh= without ddp= is the GSPMD train path and "
                    f"needs the serving mesh axes {MESH_AXES} "
                    f"(serving.mesh.build_mesh); got {mesh.axis_names}")
            shape = (int(mesh.shape["batch"]), int(mesh.shape["model"]))
            validate_mesh_shape(shape, num_heads=num_heads, knob="mesh")
            zero = _is_flat_optimizer(core.optimizer)
            if zero and core.optimizer.group_size not in (0, shape[0]):
                raise ValueError(
                    f"the flat optimizer's group_size "
                    f"({core.optimizer.group_size}) must be 0 or the "
                    f"mesh batch axis ({shape[0]}): the ZeRO shard "
                    f"count IS the batch axis on the GSPMD path")
            if param_pspec is None:
                from apex_tpu.models.gpt import gpt_param_pspec
                param_pspec = gpt_param_pspec
            self.mesh_shape = shape
            self._mesh = mesh
            self._plan = plan = _GspmdPlan(
                mesh, param_pspec,
                batch_spec if batch_spec is not None else _P(None, "batch"),
                zero=zero)
            core.acc_constraint = lambda acc: jax.tree.map(
                plan._pin, acc, plan.param_shardings(acc))

            def fn(state, batch):
                new_state, metrics = core.fused_step(state, batch)
                return (plan.constrain_state(new_state),
                        plan.constrain_metrics(metrics))
        elif mesh is not None:
            # legacy 1-D shard_map path (ddp's axis over mesh)
            if batch_spec is None:
                batch_spec = _P(None, core.ddp.axis_name)
            fn = compat_shard_map(
                fn, mesh,
                in_specs=(_P(), batch_spec),
                out_specs=(_P(), _P()),
            )
        self._jitted = (jax.jit(fn, donate_argnums=(0,)) if donate
                        else jax.jit(fn))

    def init(self, params, scaler_state: Optional[ScalerState] = None
             ) -> TrainState:
        """Fresh :class:`TrainState` (step 0, zero moments, scaler at its
        initial scale — or carry in a checkpointed ``scaler_state``).
        On the GSPMD path the params are committed to their mesh layout
        first and the whole state comes back committed (stable jit
        cache keys; pass uncommitted host params freely)."""
        if self._plan is not None:
            from apex_tpu.serving.mesh import shard_params

            params = shard_params(self._mesh, params,
                                  pspec_fn=self._plan.pspec_fn)
        state = TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=self._core.optimizer.init(params),
            scaler_state=(self._core.scaler.init() if scaler_state is None
                          else scaler_state),
        )
        if self._plan is not None:
            state = self._plan.commit_state(state)
        return state

    def step(self, state: TrainState, batch):
        _check_batch(batch, self.accum_steps)
        if self._plan is not None:
            batch = self._plan.commit_batch(batch)
        return self._jitted(state, batch)

    __call__ = step

    @property
    def program(self):
        """The raw (unjitted, un-shard_mapped) step function
        ``(state, batch) -> (state, metrics)`` — for callers embedding
        the step in their own pmap/shard_map/pjit wrapper instead of
        passing ``mesh=``."""
        return self._core.fused_step

    def alias_stats(self, state: TrainState, batch):
        """Donation audit of the compiled program: the
        ``input_output_alias`` pairs XLA actually honored. A fused step
        doing its job aliases every param + optimizer-moment + scaler
        buffer; assert ``pairs >= n_param_leaves`` in tests (lowering
        does not execute or consume the donated state)."""
        from apex_tpu.utils.hlo_audit import lowered_alias_stats

        _check_batch(batch, self.accum_steps)
        return lowered_alias_stats(self._jitted, state, batch)

    def audit_collectives(self, state: TrainState, batch,
                          num_layers: Optional[int] = None) -> dict:
        """Certify the sharded step's compiled program against the
        per-mesh collective contract — the serving mesh's audit applied
        to training. AOT-lowers from abstract sharded ShapeDtypeStructs
        (no dispatch, no donated-buffer consumption, jit cache
        untouched) and asserts:

        - :func:`apex_tpu.serving.mesh.train_expected_collectives` for
          this mesh shape — zero collectives at (1, 1); the one
          reduce-scatter + all-gather ZeRO round trip (or XLA:CPU's
          all-reduce spelling, ``alt_min_ops``) when the batch axis
          shards a flat optimizer; ``>= 2 * num_layers`` all-reduces on
          the tensor-parallel leg; never an all-to-all;
        - donation alias pairs ``>=`` the sharded param + optimizer
          leaf count (XLA drops donation silently; the positive count
          is the certification signal).

        ``num_layers`` defaults to reading the GPT block count off
        ``state.params`` (:func:`~apex_tpu.models.gpt.gpt_num_layers`);
        pass it explicitly for non-GPT trees. Returns
        ``{"collectives", "alias", "contract", "sharded_leaves"}``.
        Raises ``AssertionError`` on any violation; requires the GSPMD
        ``mesh=`` path."""
        from apex_tpu.serving.mesh import train_expected_collectives
        from apex_tpu.utils.hlo_audit import (
            abstract_sharded,
            assert_collective_contract,
            collective_stats,
            input_output_alias_stats,
        )

        if self._plan is None:
            raise ValueError(
                "audit_collectives requires the GSPMD train step "
                "(build_train_step(mesh=...) without ddp=)")
        _check_batch(batch, self.accum_steps)
        specs = self._plan.batch_shardings(batch)
        abatch = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(
                jnp.shape(x), getattr(x, "dtype", jnp.asarray(x).dtype),
                sharding=s),
            batch, specs)
        txt = (self._jitted.lower(abstract_sharded(state), abatch)
               .compile().as_text())
        # exclude_degenerate: CSE-merged scalar-constant broadcasts
        # resharded across mixed-layout leaves lower as all-to-alls
        # of a constant — no data moves; counting them would fail
        # the no-all-to-all contract on an artifact
        stats = collective_stats(txt, exclude_degenerate=True)
        if num_layers is None:
            from apex_tpu.models.gpt import gpt_num_layers

            num_layers = gpt_num_layers(state.params) or None
        contract = train_expected_collectives(
            self.mesh_shape, num_layers=num_layers, zero=self._plan.zero)
        label = f"train_step@mesh{self.mesh_shape}"
        assert_collective_contract(stats, label=label, **contract)
        alias = input_output_alias_stats(txt)
        sharded_leaves = sum(
            1 for leaf in jax.tree.leaves((state.params, state.opt_state))
            if hasattr(leaf, "ndim"))
        if self.donate and alias["pairs"] < sharded_leaves:
            raise AssertionError(
                f"{label}: XLA honored {alias['pairs']} donation alias "
                f"pair(s) but the state carries {sharded_leaves} sharded "
                f"param + optimizer leaves — donation was dropped "
                f"(layout/dtype mismatch between a donated input and "
                f"its output)")
        return {"collectives": stats, "alias": alias,
                "contract": contract, "sharded_leaves": sharded_leaves}

    def loop(self, state: TrainState, **kwargs):
        """A deferred-metrics :class:`apex_tpu.train.TrainLoop` over this
        step, starting from ``state``; keyword arguments (fault plan,
        retry, watchdog, checkpoint knobs) forward to the loop."""
        from apex_tpu.train.loop import TrainLoop

        return TrainLoop(self, state, **kwargs)


def build_train_step(
    loss_fn: Callable,
    optimizer,
    amp=None,
    ddp=None,
    accum_steps: int = 1,
    has_aux: bool = False,
    lr_schedule: Optional[Callable] = None,
    with_grad_norm: bool = False,
    donate: bool = True,
    mesh=None,
    batch_spec=None,
    param_pspec=None,
    num_heads: Optional[int] = None,
    loss_id: int = 0,
) -> TrainStep:
    """Compile forward + backward + unscale/overflow-skip + accumulation
    + DDP allreduce + fused optimizer update into ONE donated dispatch.

    Args:
      loss_fn: ``loss_fn(params, microbatch) -> loss`` (or ``(loss,
        aux)`` with ``has_aux=True``); ``microbatch`` is one slice along
        the batch's leading accumulation axis.
      optimizer: a Fused* optimizer (anything with the
        ``apply_gradients`` donation-friendly surface of
        :class:`apex_tpu.optimizers._base.FusedOptimizer`).
      amp: an :class:`~apex_tpu.amp.handle.AmpHandle` from
        ``amp.initialize`` (threads its loss scaler AND its O1 autocast
        trace wrapper), a bare :class:`LossScaler`, or None (unity
        static scale).
      ddp: optional :class:`DistributedDataParallel`; its collective
        runs once per global step, after the scan.
      accum_steps: microbatches accumulated (scanned) per optimizer
        step. Batch leaves must be ``[accum_steps, ...]``.
      lr_schedule: optional ``lr_schedule(completed_steps_i32) -> lr``.
      with_grad_norm: include the post-reduction global grad norm in the
        metrics (one extra fused reduction pass).
      donate: donate the :class:`TrainState` (in-place aliased updates).
      mesh / batch_spec: with ``ddp``, wrap the program in ``shard_map``
        over ``mesh`` (the legacy 1-D data-parallel path; ``batch_spec``
        defaults to ``P(None, ddp.axis_name)``). WITHOUT ``ddp``, a
        ``mesh`` selects the GSPMD single-dispatch path: the serving
        ``("batch", "model")`` mesh (``serving.mesh.build_mesh``), with
        tensor-parallel params via ``param_pspec``, the global batch
        sharded ``P(None, "batch")``, and — when ``optimizer`` is a
        ``DistributedFused*`` flat optimizer — ZeRO state sharded over
        the batch axis, all inside ONE donated dispatch whose contract
        :meth:`TrainStep.audit_collectives` certifies. Mesh geometry is
        validated here, at construction, with named-knob errors.
        Without ``mesh`` the caller may shard_map the returned step
        themselves (via :attr:`TrainStep.program`).
      param_pspec: GSPMD path only — ``pspec_fn(path) -> PartitionSpec``
        for each param leaf (default
        :func:`apex_tpu.models.gpt.gpt_param_pspec`); also applied (by
        trailing path) to mirrored per-leaf optimizer moments.
      num_heads: GSPMD path only — when given, the mesh ``model`` axis
        must divide it (construction-time check; the trace would
        otherwise fail deep inside attention).
    """
    sharded = mesh is not None and ddp is None
    if _is_flat_optimizer(optimizer):
        if sharded:
            bsize = dict(mesh.shape).get("batch")
            if bsize is not None and optimizer.group_size not in (
                    0, int(bsize)):
                raise ValueError(
                    f"the flat optimizer's group_size "
                    f"({optimizer.group_size}) must be 0 or the mesh "
                    f"batch axis ({int(bsize)}): the ZeRO shard count "
                    f"IS the batch axis on the GSPMD path")
            optimizer = optimizer.replace(
                flat_mode="global", mesh=mesh,
                process_group="batch",
                group_size=int(bsize) if bsize else 0)
        elif mesh is None and optimizer.mesh is not None:
            raise ValueError(
                "the flat optimizer carries a mesh but build_train_step "
                "got mesh=None; pass the same mesh (or a fresh "
                "unconfigured optimizer)")
    scaler, trace_wrapper = _resolve_scaler(amp, loss_id)
    core = _StepCore(loss_fn, optimizer, scaler, trace_wrapper, ddp,
                     accum_steps, has_aux, lr_schedule, with_grad_norm,
                     loss_id)
    return TrainStep(core, donate, mesh, batch_spec,
                     param_pspec=param_pspec, num_heads=num_heads)


class ReferenceLoop:
    """The hand-wired per-microbatch dispatch loop the fused step
    replaces — SAME math, same order, one jitted program per microbatch
    plus a separate apply program. Exists as the certification baseline
    (bit-identity in tests / ``bench_train_step``) and as an honest
    what-it-cost-before arm; do not use it to train.
    """

    def __init__(self, core: _StepCore, mesh, batch_spec):
        self._core = core
        self._mesh = mesh
        self.accum_steps = core.accum_steps
        ddp = core.ddp

        if mesh is None:
            def grad_mb(params, sst, carry, mb):
                new_carry, _ = core.microbatch(params, sst, carry, mb)
                return new_carry

            def apply_fn(state, carry):
                acc, loss_sum, inf_any = carry
                return core.apply(state, acc, loss_sum, inf_any)
        else:
            if ddp is None:
                raise ValueError("mesh= without ddp=")
            if batch_spec is None:
                batch_spec = _P(None, ddp.axis_name)

            # Between dispatches the accumulator must stay DEVICE-LOCAL
            # (the fused scan's carry never leaves its device): it rides
            # a leading world axis sharded over the mesh so each dispatch
            # resumes its own device's partial sum — squeeze the length-1
            # local block off around the shared microbatch math.
            def grad_mb(params, sst, carry, mb):
                local = jax.tree.map(lambda x: x[0], carry)
                new_local, _ = core.microbatch(params, sst, local, mb)
                return jax.tree.map(lambda x: x[None], new_local)

            def apply_fn(state, carry):
                acc, loss_sum, inf_any = jax.tree.map(lambda x: x[0],
                                                      carry)
                return core.apply(state, acc, loss_sum, inf_any)

            acc_spec = _P(ddp.axis_name)
            carry_specs = (acc_spec, acc_spec, acc_spec)
            grad_mb = compat_shard_map(
                grad_mb, mesh,
                in_specs=(_P(), _P(), carry_specs,
                          _strip_leading_axis(batch_spec)),
                out_specs=carry_specs)
            apply_fn = compat_shard_map(
                apply_fn, mesh,
                in_specs=(_P(), carry_specs),
                out_specs=(_P(), _P()))
        self._grad_mb = jax.jit(grad_mb)
        self._apply = jax.jit(apply_fn)

    def init(self, params, scaler_state=None) -> TrainState:
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=self._core.optimizer.init(params),
            scaler_state=(self._core.scaler.init() if scaler_state is None
                          else scaler_state),
        )

    def _zero_carry(self, params):
        acc, loss_sum, inf_any = self._core.zero_carry(params)
        if self._mesh is not None:
            world = self._mesh.devices.size

            def widen(x):
                return jnp.zeros((world,) + jnp.shape(x), x.dtype)

            acc = jax.tree.map(widen, acc)
            loss_sum, inf_any = widen(loss_sum), widen(inf_any)
        return acc, loss_sum, inf_any

    def step(self, state: TrainState, batch):
        _check_batch(batch, self.accum_steps)
        carry = self._zero_carry(state.params)
        for i in range(self.accum_steps):
            mb = jax.tree.map(lambda x: x[i], batch)
            carry = self._grad_mb(state.params, state.scaler_state,
                                  carry, mb)
        return self._apply(state, carry)

    __call__ = step


def build_reference_loop(
    loss_fn: Callable,
    optimizer,
    amp=None,
    ddp=None,
    accum_steps: int = 1,
    lr_schedule: Optional[Callable] = None,
    with_grad_norm: bool = False,
    mesh=None,
    batch_spec=None,
    loss_id: int = 0,
) -> ReferenceLoop:
    """Build the hand-wired per-microbatch dispatch loop with the same
    configuration surface as :func:`build_train_step` (no ``donate`` —
    the pre-builder world didn't donate, that's the point)."""
    scaler, trace_wrapper = _resolve_scaler(amp, loss_id)
    core = _StepCore(loss_fn, optimizer, scaler, trace_wrapper, ddp,
                     accum_steps, False, lr_schedule, with_grad_norm,
                     loss_id)
    return ReferenceLoop(core, mesh, batch_spec)
