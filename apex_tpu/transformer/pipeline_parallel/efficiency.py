"""Pipeline schedule efficiency: tick accounting + empirical measurement.

VERDICT r4 weak #5: the SPMD schedules argued their efficiency
("total ticks = M + pp - 1") but nothing MEASURED it. This module makes
the schedule contract checkable two ways:

- :func:`tick_accounting` — the analytic contract of the scan schedules
  in ``schedules.py``: per-stage active ticks, total ticks, bubble
  fraction, and work-normalized time units, for both the 1F1B-role
  schedule (``num_chunks=1``) and the interleaved virtual-pipeline
  schedule (``num_chunks=v``). These are the same formulas the Megatron
  paper derives for 1F1B (bubble = (pp-1)/(m+pp-1)) and its interleaved
  variant (bubble ≈ (pp-1)/(v*m+pp-1) at 1/v per-tick work) — the
  upstream ``apex/transformer/pipeline_parallel/schedules.py``
  warmup/steady/cooldown structure realizes the identical accounting
  imperatively.
- :func:`measure_pipeline_ticks` — an empirical wall-clock fit on the
  live mesh (the 8-device CPU sim in tests; a real pod in production):
  time the compiled pipeline at several microbatch counts, fit
  ``T(m) = a*(m + pp - 1) + c``, and compare the fitted per-tick slope
  ``a`` against a directly-timed single stage application. On a host
  that time-shares the virtual devices (the 1-core CI box) every tick
  costs ~pp stage-computations, so a healthy schedule shows
  ``a / t_stage ≈ pp``; a schedule that degenerated into nested
  sequential sweeps costs ~pp² per effective microbatch and blows that
  ratio up. (With one hardware thread, slope-vs-m alone CANNOT separate
  the two — both are affine in m — which is why the stage-normalized
  slope is the reported discriminator.)
"""

from __future__ import annotations

import time
from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P


def tick_accounting(pp: int, num_microbatches: int,
                    num_chunks: int = 1) -> Dict[str, float]:
    """Analytic schedule accounting (see module docstring).

    Returns a dict with ``total_ticks``, ``active_ticks_per_stage``,
    ``utilization``, ``bubble_fraction``, and ``time_units`` — the
    work-normalized wall-time proxy (per-tick cost is 1/num_chunks of a
    full stage, so interleaving shrinks the bubble's absolute cost even
    though it adds ticks)."""
    if pp < 1 or num_microbatches < 1 or num_chunks < 1:
        raise ValueError("pp, num_microbatches, num_chunks must be >= 1")
    m, v = num_microbatches, num_chunks
    total_ticks = v * m + pp - 1
    active = v * m
    return {
        "total_ticks": total_ticks,
        "active_ticks_per_stage": active,
        "utilization": active / total_ticks,
        "bubble_fraction": (pp - 1) / total_ticks,
        "time_units": total_ticks / v,
    }


def _time_once(fn, *args) -> float:
    t0 = time.perf_counter()
    jax.block_until_ready(fn(*args))
    return time.perf_counter() - t0


def _build_pipeline(pp: int, m: int, hidden: int, mb_size: int,
                    num_chunks: int = 1):
    """(jitted shard_map'd pipeline fwd, example args) on the first
    ``pp`` local devices."""
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        spmd_pipeline,
        spmd_pipeline_interleaved,
    )

    mesh = parallel_state.get_mesh()
    rng = np.random.RandomState(0)
    v = num_chunks

    def stage_fn(wl, x, mb_idx):
        return jnp.tanh(x @ wl) @ wl.T * 0.5

    xs = jnp.asarray(rng.randn(m, mb_size, hidden).astype("f4"))
    if v == 1:
        w = jnp.asarray(rng.randn(pp, hidden, hidden).astype("f4") * 0.1)

        def run(w_stacked, xs):
            wl = w_stacked.reshape(hidden, hidden)
            return spmd_pipeline(stage_fn, wl, xs, num_microbatches=m,
                                 remat=False)
    else:
        w = jnp.asarray(
            rng.randn(v, pp, hidden, hidden).astype("f4") * 0.1)

        def run(w_stacked, xs):
            wl = w_stacked.reshape(v, hidden, hidden)
            return spmd_pipeline_interleaved(
                stage_fn, wl, xs, num_microbatches=m,
                num_model_chunks=v, remat=False)

    jitted = jax.jit(jax.shard_map(
        run, mesh=mesh,
        in_specs=(P("pipeline") if v == 1 else P(None, "pipeline"), P()),
        out_specs=P("pipeline")))
    return jitted, (w, xs)


def compiled_tick_count(pp: int, num_microbatches: int,
                        num_chunks: int = 1, hidden: int = 32,
                        mb_size: int = 2) -> int:
    """Tick count of the COMPILED schedule, read from the optimized
    HLO — the deterministic counterpart of :func:`measure_pipeline_ticks`
    (wall-clock on a time-shared CI host is too noisy to pin a tick
    count; the compiled program is exact).

    The scan lowers to a single `while` loop whose carry holds the
    ``jnp.arange(total_ticks)`` tick array as the one 1-D s32 operand —
    its length IS the trip count. Returns that length."""
    import re

    from apex_tpu.transformer import parallel_state

    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=pp,
        virtual_pipeline_model_parallel_size_=(
            num_chunks if num_chunks > 1 else None),
        devices=jax.devices()[:pp])
    try:
        jitted, args = _build_pipeline(pp, num_microbatches, hidden,
                                       mb_size, num_chunks)
        hlo = jitted.lower(*args).compile().as_text()
        counts = set()
        for line in hlo.splitlines():
            if not re.search(r"=\s*\(.*\)\s+while\(", line):
                continue
            counts.update(int(n) for n in
                          re.findall(r"s32\[(\d+)\]", line))
        if not counts:
            raise RuntimeError("no while-loop tick array found in HLO")
        return max(counts)
    finally:
        parallel_state.destroy_model_parallel()


def measure_pipeline_ticks(pp: int, microbatch_counts: Sequence[int] = (2, 8),
                           hidden: int = 256, mb_size: int = 4,
                           reps: int = 3) -> Dict[str, float]:
    """Wall-clock the compiled ``spmd_pipeline`` forward at several
    microbatch counts on the first ``pp`` local devices and fit
    ``T(m) = a * (m + pp - 1) + c``.

    Returns ``per_tick_seconds`` (fitted a), ``fit_residual`` (relative
    RMS of the fit), ``measured`` ({m: seconds}), ``stage_seconds``
    (directly-timed one stage application on one device), and
    ``slope_over_stage_cost`` = a / stage_seconds — the schedule-health
    discriminator (see module docstring): ≈pp on a time-shared host,
    ≈1 with one hardware thread per device, ≈pp² if the scan
    serialized."""
    from apex_tpu.transformer import parallel_state

    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=pp, devices=jax.devices()[:pp])
    try:
        rng = np.random.RandomState(0)
        measured = {}
        w = None
        for m in microbatch_counts:
            jitted, (w, xs) = _build_pipeline(pp, m, hidden, mb_size)
            _time_once(jitted, w, xs)  # compile + warm
            measured[m] = min(_time_once(jitted, w, xs)
                              for _ in range(reps))

        # direct cost of ONE stage application on one device (the
        # normalizer for the schedule-health ratio)
        def stage_fn(wl, x):
            return jnp.tanh(x @ wl) @ wl.T * 0.5

        x1 = jnp.asarray(rng.randn(mb_size, hidden).astype("f4"))
        w1 = w[0]
        stage_jit = jax.jit(stage_fn)
        _time_once(stage_jit, w1, x1)
        stage_seconds = min(_time_once(stage_jit, w1, x1)
                            for _ in range(max(reps * 3, 8)))

        ms = np.asarray(sorted(measured), np.float64)
        ts = np.asarray([measured[int(m)] for m in ms])
        A = np.stack([ms + pp - 1, np.ones_like(ms)], axis=1)
        (a, c), *_ = np.linalg.lstsq(A, ts, rcond=None)
        resid = ts - A @ np.asarray([a, c])
        return {
            "per_tick_seconds": float(a),
            "fit_residual": float(np.sqrt(np.mean(resid ** 2)) / np.mean(ts)),
            "stage_seconds": float(stage_seconds),
            "slope_over_stage_cost": float(a / stage_seconds),
            "measured": {int(m): float(measured[int(m)]) for m in ms},
        }
    finally:
        parallel_state.destroy_model_parallel()
