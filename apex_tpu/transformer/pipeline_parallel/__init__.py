"""apex_tpu.transformer.pipeline_parallel — SPMD collective-permute
pipelining (SURVEY.md §2.3 PP row)."""

from apex_tpu.transformer.pipeline_parallel import p2p_communication  # noqa: F401
from apex_tpu.transformer.pipeline_parallel.schedules import (  # noqa: F401
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    spmd_pipeline,
    spmd_pipeline_interleaved,
)
