"""Stage-to-stage communication primitives.

Rebuild of ``apex/transformer/pipeline_parallel/p2p_communication.py``
(SURVEY.md §3.5): the reference wraps batched NCCL isend/irecv with shape
negotiation (``_communicate``). On TPU, point-to-point transfer between
pipeline stages is ``lax.ppermute`` over the ``pipeline`` axis — shapes
are static under jit, so the negotiation machinery disappears; each helper
keeps its reference name/direction. All helpers require the pipeline axis
bound (inside shard_map).

These are the building blocks :mod:`schedules` uses; exposed for users
porting custom schedules.
"""

from __future__ import annotations

import jax

from apex_tpu.transformer import parallel_state


def _perm_forward():
    pp = parallel_state.get_pipeline_model_parallel_world_size()
    return [(i, (i + 1) % pp) for i in range(pp)]


def _perm_backward():
    pp = parallel_state.get_pipeline_model_parallel_world_size()
    return [(i, (i - 1) % pp) for i in range(pp)]


def send_forward(x, axis_name=None):
    """Ship activations to the next stage (reference: ``send_forward``).
    Returns what this stage receives from its predecessor."""
    axis = axis_name or parallel_state.PIPELINE_AXIS
    return jax.lax.ppermute(x, axis, _perm_forward())


def send_backward(x, axis_name=None):
    """Ship gradients to the previous stage (reference: ``send_backward``)."""
    axis = axis_name or parallel_state.PIPELINE_AXIS
    return jax.lax.ppermute(x, axis, _perm_backward())


def recv_forward(x, axis_name=None):
    """Alias of :func:`send_forward` viewed from the receiver (the
    reference's paired recv; ppermute is symmetric)."""
    return send_forward(x, axis_name)


def recv_backward(x, axis_name=None):
    return send_backward(x, axis_name)


def send_forward_recv_backward(fwd, bwd, axis_name=None):
    """Bidirectional exchange (reference name preserved): one hop forward
    for activations and one hop backward for gradients, issued together so
    XLA can overlap them on opposite ICI directions."""
    return send_forward(fwd, axis_name), send_backward(bwd, axis_name)


def send_backward_recv_forward(bwd, fwd, axis_name=None):
    return send_backward(bwd, axis_name), send_forward(fwd, axis_name)
