"""Pipeline-parallel schedules as SPMD collective-permute pipelines.

Rebuild of ``apex/transformer/pipeline_parallel/schedules.py`` (SURVEY.md
§3.5): the reference drives 1F1B with explicit NCCL send/recv per
microbatch hop (warmup = ``pp_size - rank - 1`` forwards, steady-state
alternation, cooldown drain), because torch must schedule imperatively.

TPU design (SURVEY.md §7 hard part 4): the schedule is DATA FLOW, not
control flow. Every stage runs the same program: a ``lax.scan`` over
``num_microbatches + pp - 1`` ticks in which each device

  1. selects its current input (stage 0: the next microbatch; others: the
     activation received from the left neighbor),
  2. applies its stage's layer stack,
  3. ``ppermute``\\ s the activation to the right neighbor.

The last stage accumulates per-microbatch outputs/losses. Differentiating
through the scan gives the reverse pipeline (cooldown) automatically, with
activation rematerialization via ``jax.checkpoint`` on the stage fn; XLA's
latency-hiding scheduler overlaps the ppermute with compute — which is
exactly the role of the reference's explicit 1F1B interleaving. Microbatch
bookkeeping (SURVEY.md: ``apex/transformer/microbatches.py``) reduces to
the ``num_microbatches`` argument.

Used inside ``shard_map`` over the ``pipeline`` mesh axis, with each
device holding its stage's parameter shard (stack parameters along a
leading ``pp`` axis and shard it over ``pipeline``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state


def _axis():
    return parallel_state.PIPELINE_AXIS


def _size_of(shape):
    n = 1
    for d in shape:
        n *= d
    return n


def pack_carry(x, carry_struct):
    """Pack an arbitrary-shaped stage boundary value into the fixed
    pipeline carry buffer (the shape-negotiation half the reference does
    with ``_communicate``'s shape handshake — SURVEY §2.3 PP row: NCCL
    can negotiate shapes per hop; an SPMD scan carry cannot, so
    shape-CHANGING stages flatten/pad into a carry sized for the largest
    boundary instead).

    Same-kind payloads (float into a float carry, int into an int carry)
    round-trip via ``astype`` (exact when the carry dtype is at least as
    wide); cross-kind payloads are BIT-cast, which requires a 4-byte
    carry dtype (f32/i32) — a 2-byte carry with an int payload raises
    rather than corrupting token ids."""
    flat = x.reshape(-1)
    x_int = jnp.issubdtype(x.dtype, jnp.integer)
    c_int = jnp.issubdtype(carry_struct.dtype, jnp.integer)
    if x_int == c_int:
        flat = flat.astype(carry_struct.dtype)
    else:
        if jnp.dtype(carry_struct.dtype).itemsize != 4:
            raise ValueError(
                f"pack_carry: cross-kind payload ({x.dtype} into "
                f"{carry_struct.dtype} carry) needs a 4-byte carry dtype "
                "(f32/i32) for a lossless bitcast")
        src = jnp.int32 if x_int else jnp.float32
        flat = jax.lax.bitcast_convert_type(flat.astype(src),
                                            carry_struct.dtype)
    size = _size_of(carry_struct.shape)
    if flat.size > size:
        raise ValueError(
            f"pack_carry: value of shape {x.shape} ({flat.size} elems) "
            f"exceeds the carry capacity {carry_struct.shape} ({size})")
    return jnp.pad(flat, (0, size - flat.size)).reshape(carry_struct.shape)


def unpack_carry(carry, shape, dtype):
    """Inverse of :func:`pack_carry`: slice the leading elements of the
    carry buffer back into ``(shape, dtype)``."""
    flat = carry.reshape(-1)[:_size_of(shape)]
    d_int = jnp.issubdtype(jnp.dtype(dtype), jnp.integer)
    c_int = jnp.issubdtype(carry.dtype, jnp.integer)
    if d_int == c_int:
        return flat.astype(dtype).reshape(shape)
    dst = jnp.int32 if d_int else jnp.float32
    return jax.lax.bitcast_convert_type(flat, dst).astype(dtype).reshape(shape)


def _shift_right(x, axis_name, pp):
    """Send to stage s+1; stage 0 receives stage pp-1's value (ignored)."""
    from apex_tpu.transformer.pipeline_parallel import p2p_communication

    return p2p_communication.send_forward(x, axis_name)


def _infer_carry_mark(fn, probe_params, microbatches, axis, name):
    """Varying-axes set for the scan carry + stage_fn shape validation.

    The carry is device-varying from tick 1 on (ppermute), and the stage
    fn may introduce MORE varying axes (e.g. TP collectives inside the
    stage make activations tensor-varying). The scan needs a stable
    carry type, so infer the fixed point of the stage fn's output
    varying-set via eval_shape (abstract — no compute is added). The
    first probe also validates the shape/dtype-preservation contract.
    """
    from apex_tpu.utils.collectives import mark_varying

    mb_shape = microbatches.shape[1:]
    mb_vma = frozenset(getattr(jax.typeof(microbatches), "vma", None) or ())
    vma = frozenset({axis}) | mb_vma  # injected microbatches carry their own
    converged = False
    for it in range(4):  # the varying-set only grows and mesh axes are few
        def _probe(vma=vma):
            x = mark_varying(jnp.zeros(mb_shape, microbatches.dtype),
                             tuple(vma))
            return fn(probe_params, x, jnp.int32(0))

        out_spec = jax.eval_shape(_probe)
        if it == 0 and (out_spec.shape, out_spec.dtype) != (
                mb_shape, microbatches.dtype):
            raise ValueError(
                f"{name} stage_fn must preserve the microbatch "
                f"shape/dtype (the scan carry): got {out_spec.shape}/"
                f"{out_spec.dtype} from input {mb_shape}/"
                f"{microbatches.dtype}. Fold shape-changing ops (embedding "
                "lookup, logit projection) inside the first/last stage's "
                "fn, gated on axis_index."
            )
        out_vma = frozenset(getattr(out_spec, "vma", None) or ()) | vma
        if out_vma == vma:
            converged = True
            break
        vma = out_vma
    if not converged:
        raise RuntimeError(
            f"{name} could not infer a stable varying-axes set for "
            f"the scan carry (last iterate: {sorted(vma)}). The stage_fn's "
            "output varying-set must reach a fixed point; check for "
            "collectives over axes not in the current mesh."
        )
    return tuple(vma)


def spmd_pipeline(
    stage_fn: Callable,
    stage_params,
    microbatches,
    *,
    num_microbatches: int,
    remat: bool = True,
    axis_name: Optional[str] = None,
    carry_struct: Optional[jax.ShapeDtypeStruct] = None,
):
    """Run a pipelined forward pass.

    Args:
      stage_fn: ``(params, x, microbatch_index) -> x`` — one stage's
        compute, applied by every device to its local params.
      stage_params: this device's stage parameters (inside shard_map these
        are the local shard of a pp-stacked pytree).
      microbatches: (num_microbatches, mb, ...) inputs, replicated across
        the pipeline axis (stage 0 reads them; other stages ignore).
      num_microbatches: M. Total ticks = M + pp - 1.
      remat: rematerialize stage activations in backward
        (``jax.checkpoint``), the reference's activation-recompute default
        for pipeline training.

    Returns:
      (num_microbatches, mb, ...) outputs as produced by the LAST stage
      (valid there; other stages hold garbage — reduce over the axis or
      read stage pp-1's shard).

    Shape-changing pipelines (the reference's ``_communicate`` negotiates
    shapes per NCCL hop; a scan carry cannot): pass ``carry_struct``, a
    ``jax.ShapeDtypeStruct`` sized for the LARGEST stage boundary. Then
    ``microbatches`` entries and every ``stage_fn`` output must be
    carry-shaped — use :func:`pack_carry` / :func:`unpack_carry` at each
    boundary (embedding ids → hidden → logits all travel in the one
    padded buffer; each stage unpacks the shape it knows, switched on
    ``axis_index``). Without ``carry_struct`` the carry is the
    microbatch shape/dtype and ``stage_fn`` must be shape- and
    dtype-preserving; violations raise immediately with the offending
    shapes rather than an opaque scan carry-type error.
    """
    axis = axis_name or _axis()
    if carry_struct is not None and (
            tuple(microbatches.shape[1:]) != tuple(carry_struct.shape)
            or microbatches.dtype != carry_struct.dtype):
        raise ValueError(
            f"with carry_struct {carry_struct.shape}/{carry_struct.dtype}, "
            f"microbatches must be pre-packed to that shape (got "
            f"{microbatches.shape[1:]}/{microbatches.dtype}); use "
            "pack_carry on each microbatch")
    pp = parallel_state.get_pipeline_model_parallel_world_size()
    stage = jax.lax.axis_index(axis)
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    mb_shape = microbatches.shape[1:]
    total_ticks = num_microbatches + pp - 1

    def tick(carry, t):
        state, outputs = carry
        mb_idx = t - stage  # microbatch this stage works on at tick t
        active = (mb_idx >= 0) & (mb_idx < num_microbatches)

        # stage 0 injects a fresh microbatch; others use the received state
        inject = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, num_microbatches - 1), keepdims=False
        )
        x_in = jnp.where(stage == 0, inject, state)

        y = fn(stage_params, x_in, mb_idx)
        # inactive ticks pass state through unchanged (keeps shapes static)
        y = jnp.where(active, y, state)

        # last stage records its finished microbatch
        out_idx = jnp.clip(t - (pp - 1), 0, num_microbatches - 1)
        record = (stage == pp - 1) & (t >= pp - 1)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(record, y, jax.lax.dynamic_index_in_dim(outputs, out_idx, keepdims=False)),
            out_idx,
            axis=0,
        )

        # ship activations rightward for the next tick
        state = _shift_right(y, axis, pp) if pp > 1 else y
        return (state, outputs), None

    from apex_tpu.utils.collectives import mark_varying

    mark = _infer_carry_mark(fn, stage_params, microbatches, axis,
                             "spmd_pipeline")

    init_state = mark_varying(jnp.zeros(mb_shape, microbatches.dtype), mark)
    init_out = mark_varying(
        jnp.zeros((num_microbatches,) + mb_shape, microbatches.dtype), mark)
    (_, outputs), _ = jax.lax.scan(
        tick, (init_state, init_out), jnp.arange(total_ticks)
    )
    return outputs


def _pipelined_loss_and_grad(pipeline_call, stage_params, *,
                             num_microbatches, loss_fn, axis):
    """Shared loss/grad wrapper for both schedules: per-microbatch loss on
    the last stage, mean over microbatches, psum-broadcast, value_and_grad
    through the scan (AD gives the reverse schedule)."""
    pp = parallel_state.get_pipeline_model_parallel_world_size()

    def pipeline_loss(params):
        outs = pipeline_call(params)
        per_mb = jax.vmap(loss_fn)(outs, jnp.arange(num_microbatches))
        local = jnp.mean(per_mb)
        stage = jax.lax.axis_index(axis)
        # only the last stage's loss is real; zero others then sum
        return jax.lax.psum(jnp.where(stage == pp - 1, local, 0.0), axis)

    return jax.value_and_grad(pipeline_loss)(stage_params)


def forward_backward_pipelining_without_interleaving(
    forward_step_fn: Callable,
    batch,
    stage_params,
    *,
    num_microbatches: int,
    loss_fn: Callable,
    remat: bool = True,
    axis_name: Optional[str] = None,
):
    """1F1B-equivalent pipelined loss + gradients (reference:
    ``forward_backward_pipelining_without_interleaving``).

    Args:
      forward_step_fn: ``(params, x, mb_idx) -> activation`` per stage.
      batch: (num_microbatches, mb, ...) microbatched inputs.
      stage_params: per-stage local params (pp-stacked, sharded).
      loss_fn: ``(last_stage_output, mb_idx) -> scalar`` per microbatch;
        evaluated on the last stage, mean-reduced over microbatches.

    Returns:
      (loss, grads) with loss replicated across stages and grads local to
      each stage's params — the reference returns per-stage losses and
      leaves grads in ``param.grad`` similarly.
    """
    axis = axis_name or _axis()
    return _pipelined_loss_and_grad(
        lambda params: spmd_pipeline(
            forward_step_fn, params, batch,
            num_microbatches=num_microbatches, remat=remat, axis_name=axis),
        stage_params, num_microbatches=num_microbatches,
        loss_fn=loss_fn, axis=axis)


def spmd_pipeline_interleaved(
    stage_fn: Callable,
    stage_params,
    microbatches,
    *,
    num_microbatches: int,
    num_model_chunks: int,
    remat: bool = True,
    axis_name: Optional[str] = None,
):
    """Interleaved (virtual-pipeline) forward pass as a CIRCULAR pipeline.

    Reference: the interleaved path of
    ``forward_backward_pipelining_with_interleaving`` — each device owns
    ``v = num_model_chunks`` model chunks; global stage ``c*pp + r``
    lives on device ``r``. The reference cuts the bubble from
    ``(pp-1)/m`` to ``(pp-1)/(v*m)`` by interleaving chunk compute; the
    SPMD dataflow analog is a circular schedule: microbatches travel the
    device ring ``v`` times, re-entering device 0 at the next chunk one
    tick after leaving device ``pp-1`` (the ppermute wraparound delivers
    exactly on time), in groups of ``pp`` so every device computes one
    (chunk, microbatch) pair per tick with no conflicts.

    Tick math (``u = t - stage``, the device's stream position):
    ``group = u // (v*pp)``, ``chunk = (u % (v*pp)) // pp``,
    ``mb = group*pp + u % pp``. Total ticks ``v*m + pp - 1`` — the
    bubble is ``pp - 1`` single-CHUNK units vs the non-interleaved
    schedule's ``pp - 1`` whole-stage (= v-chunk) units: the 1/v bubble
    reduction the reference's interleaving exists for.

    Args:
      stage_fn: ``(chunk_params, x, microbatch_index) -> x`` — ONE model
        chunk's compute (shape/dtype-preserving, as in spmd_pipeline).
      stage_params: pytree whose leaves carry a leading
        ``num_model_chunks`` axis: this device's v chunk params.
      microbatches: (num_microbatches, mb, ...); num_microbatches must
        be divisible by pp (the reference asserts the same for its
        interleaved schedule).

    Returns:
      (num_microbatches, mb, ...) final-chunk outputs, valid on the last
      stage (as in spmd_pipeline).
    """
    axis = axis_name or _axis()
    pp = parallel_state.get_pipeline_model_parallel_world_size()
    v = int(num_model_chunks)
    if v < 1:
        raise ValueError(f"num_model_chunks must be >= 1, got {v}")
    if num_microbatches % pp != 0:
        raise ValueError(
            f"interleaved schedule requires num_microbatches "
            f"({num_microbatches}) divisible by pipeline world size ({pp}), "
            "matching the reference assertion")
    stage = jax.lax.axis_index(axis)
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    chunk0 = jax.tree.map(
        lambda p: jax.lax.index_in_dim(p, 0, keepdims=False), stage_params)
    mb_shape = microbatches.shape[1:]
    total_ticks = v * num_microbatches + pp - 1

    def tick(carry, t):
        state, outputs = carry
        u = t - stage
        group = u // (v * pp)
        within = u % (v * pp)
        chunk = within // pp
        mb_idx = group * pp + u % pp
        active = (u >= 0) & (mb_idx >= 0) & (mb_idx < num_microbatches)

        chunk_params = jax.tree.map(
            lambda p: jax.lax.dynamic_index_in_dim(
                p, jnp.clip(chunk, 0, v - 1), keepdims=False),
            stage_params)

        inject = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.clip(mb_idx, 0, num_microbatches - 1),
            keepdims=False)
        x_in = jnp.where((stage == 0) & (chunk == 0), inject, state)

        y = fn(chunk_params, x_in, mb_idx)
        y = jnp.where(active, y, state)

        record = (stage == pp - 1) & (chunk == v - 1) & active
        out_idx = jnp.clip(mb_idx, 0, num_microbatches - 1)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(record, y,
                      jax.lax.dynamic_index_in_dim(outputs, out_idx,
                                                   keepdims=False)),
            out_idx,
            axis=0,
        )

        state = _shift_right(y, axis, pp) if pp > 1 else y
        return (state, outputs), None

    from apex_tpu.utils.collectives import mark_varying

    mark = _infer_carry_mark(fn, chunk0, microbatches, axis,
                             "spmd_pipeline_interleaved")

    init_state = mark_varying(jnp.zeros(mb_shape, microbatches.dtype), mark)
    init_out = mark_varying(
        jnp.zeros((num_microbatches,) + mb_shape, microbatches.dtype), mark)
    (_, outputs), _ = jax.lax.scan(
        tick, (init_state, init_out), jnp.arange(total_ticks)
    )
    return outputs


def forward_backward_pipelining_with_interleaving(
    forward_step_fn: Callable,
    batch,
    stage_params,
    *,
    num_microbatches: int,
    loss_fn: Callable,
    num_model_chunks: Optional[int] = None,
    remat: bool = True,
    axis_name: Optional[str] = None,
):
    """Interleaved 1F1B-equivalent loss + grads (reference name).

    ``stage_params`` leaves carry a leading ``num_model_chunks`` axis
    (inferred from the first leaf when not given). Loss is evaluated on
    the last stage over final-chunk outputs; AD through the circular
    scan produces the reverse interleaved schedule.
    """
    axis = axis_name or _axis()
    if num_model_chunks is None:
        num_model_chunks = jax.tree.leaves(stage_params)[0].shape[0]
    return _pipelined_loss_and_grad(
        lambda params: spmd_pipeline_interleaved(
            forward_step_fn, params, batch,
            num_microbatches=num_microbatches,
            num_model_chunks=num_model_chunks, remat=remat, axis_name=axis),
        stage_params, num_microbatches=num_microbatches,
        loss_fn=loss_fn, axis=axis)


def get_forward_backward_func(virtual_pipeline_model_parallel_size=None,
                              pipeline_model_parallel_size=None):
    """Reference dispatcher: ``virtual_pipeline_model_parallel_size``
    selects the interleaved (circular) schedule; otherwise the plain
    SPMD pipeline."""
    if (virtual_pipeline_model_parallel_size is not None
            and virtual_pipeline_model_parallel_size > 1):
        return forward_backward_pipelining_with_interleaving
    return forward_backward_pipelining_without_interleaving
