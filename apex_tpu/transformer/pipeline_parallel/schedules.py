"""Pipeline-parallel schedules as SPMD collective-permute pipelines.

Rebuild of ``apex/transformer/pipeline_parallel/schedules.py`` (SURVEY.md
§3.5): the reference drives 1F1B with explicit NCCL send/recv per
microbatch hop (warmup = ``pp_size - rank - 1`` forwards, steady-state
alternation, cooldown drain), because torch must schedule imperatively.

TPU design (SURVEY.md §7 hard part 4): the schedule is DATA FLOW, not
control flow. Every stage runs the same program: a ``lax.scan`` over
``num_microbatches + pp - 1`` ticks in which each device

  1. selects its current input (stage 0: the next microbatch; others: the
     activation received from the left neighbor),
  2. applies its stage's layer stack,
  3. ``ppermute``\\ s the activation to the right neighbor.

The last stage accumulates per-microbatch outputs/losses. Differentiating
through the scan gives the reverse pipeline (cooldown) automatically, with
activation rematerialization via ``jax.checkpoint`` on the stage fn; XLA's
latency-hiding scheduler overlaps the ppermute with compute — which is
exactly the role of the reference's explicit 1F1B interleaving. Microbatch
bookkeeping (SURVEY.md: ``apex/transformer/microbatches.py``) reduces to
the ``num_microbatches`` argument.

Used inside ``shard_map`` over the ``pipeline`` mesh axis, with each
device holding its stage's parameter shard (stack parameters along a
leading ``pp`` axis and shard it over ``pipeline``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state


def _axis():
    return parallel_state.PIPELINE_AXIS


def _shift_right(x, axis_name, pp):
    """Send to stage s+1; stage 0 receives stage pp-1's value (ignored)."""
    from apex_tpu.transformer.pipeline_parallel import p2p_communication

    return p2p_communication.send_forward(x, axis_name)


def spmd_pipeline(
    stage_fn: Callable,
    stage_params,
    microbatches,
    *,
    num_microbatches: int,
    remat: bool = True,
    axis_name: Optional[str] = None,
):
    """Run a pipelined forward pass.

    Args:
      stage_fn: ``(params, x, microbatch_index) -> x`` — one stage's
        compute, applied by every device to its local params.
      stage_params: this device's stage parameters (inside shard_map these
        are the local shard of a pp-stacked pytree).
      microbatches: (num_microbatches, mb, ...) inputs, replicated across
        the pipeline axis (stage 0 reads them; other stages ignore).
      num_microbatches: M. Total ticks = M + pp - 1.
      remat: rematerialize stage activations in backward
        (``jax.checkpoint``), the reference's activation-recompute default
        for pipeline training.

    Returns:
      (num_microbatches, mb, ...) outputs as produced by the LAST stage
      (valid there; other stages hold garbage — reduce over the axis or
      read stage pp-1's shard).

    Constraint (differs from the reference's shape-negotiating
    ``_communicate``): the scan carry is fixed to the microbatch
    shape/dtype, so ``stage_fn`` must be shape- and dtype-preserving.
    Shape-changing stages (token ids → embeddings, hidden → logits) must
    fold the change inside one stage (embed at the top of stage 0's fn,
    project at the bottom of the last stage's, switched on
    ``axis_index``). Violations raise immediately with the offending
    shapes rather than an opaque scan carry-type error.
    """
    axis = axis_name or _axis()
    pp = parallel_state.get_pipeline_model_parallel_world_size()
    stage = jax.lax.axis_index(axis)
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    mb_shape = microbatches.shape[1:]
    total_ticks = num_microbatches + pp - 1

    def tick(carry, t):
        state, outputs = carry
        mb_idx = t - stage  # microbatch this stage works on at tick t
        active = (mb_idx >= 0) & (mb_idx < num_microbatches)

        # stage 0 injects a fresh microbatch; others use the received state
        inject = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, num_microbatches - 1), keepdims=False
        )
        x_in = jnp.where(stage == 0, inject, state)

        y = fn(stage_params, x_in, mb_idx)
        # inactive ticks pass state through unchanged (keeps shapes static)
        y = jnp.where(active, y, state)

        # last stage records its finished microbatch
        out_idx = jnp.clip(t - (pp - 1), 0, num_microbatches - 1)
        record = (stage == pp - 1) & (t >= pp - 1)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(record, y, jax.lax.dynamic_index_in_dim(outputs, out_idx, keepdims=False)),
            out_idx,
            axis=0,
        )

        # ship activations rightward for the next tick
        state = _shift_right(y, axis, pp) if pp > 1 else y
        return (state, outputs), None

    # The carry is device-varying from tick 1 on (ppermute), and the stage
    # fn may introduce MORE varying axes (e.g. TP collectives inside the
    # stage make activations tensor-varying). The scan needs a stable carry
    # type, so infer the fixed point of the stage fn's output varying-set
    # via eval_shape (abstract — no compute is added).
    from apex_tpu.utils.collectives import mark_varying

    try:
        mb_vma = frozenset(jax.typeof(microbatches).vma)
    except (AttributeError, TypeError):
        mb_vma = frozenset()
    vma = frozenset({axis}) | mb_vma  # injected microbatches carry their own
    converged = False
    for it in range(4):  # the varying-set only grows and mesh axes are few
        def _probe(vma=vma):
            x = mark_varying(jnp.zeros(mb_shape, microbatches.dtype), tuple(vma))
            return fn(stage_params, x, jnp.int32(0))

        out_spec = jax.eval_shape(_probe)
        if it == 0 and (out_spec.shape, out_spec.dtype) != (
                mb_shape, microbatches.dtype):
            raise ValueError(
                "spmd_pipeline stage_fn must preserve the microbatch "
                f"shape/dtype (the scan carry): got {out_spec.shape}/"
                f"{out_spec.dtype} from input {mb_shape}/"
                f"{microbatches.dtype}. Fold shape-changing ops (embedding "
                "lookup, logit projection) inside the first/last stage's "
                "fn, gated on axis_index."
            )
        out_vma = frozenset(getattr(out_spec, "vma", ())) | vma
        if out_vma == vma:
            converged = True
            break
        vma = out_vma
    if not converged:
        raise RuntimeError(
            "spmd_pipeline could not infer a stable varying-axes set for "
            f"the scan carry (last iterate: {sorted(vma)}). The stage_fn's "
            "output varying-set must reach a fixed point; check for "
            "collectives over axes not in the current mesh."
        )
    mark = tuple(vma)

    init_state = mark_varying(jnp.zeros(mb_shape, microbatches.dtype), mark)
    init_out = mark_varying(
        jnp.zeros((num_microbatches,) + mb_shape, microbatches.dtype), mark)
    (_, outputs), _ = jax.lax.scan(
        tick, (init_state, init_out), jnp.arange(total_ticks)
    )
    return outputs


def forward_backward_pipelining_without_interleaving(
    forward_step_fn: Callable,
    batch,
    stage_params,
    *,
    num_microbatches: int,
    loss_fn: Callable,
    remat: bool = True,
    axis_name: Optional[str] = None,
):
    """1F1B-equivalent pipelined loss + gradients (reference:
    ``forward_backward_pipelining_without_interleaving``).

    Args:
      forward_step_fn: ``(params, x, mb_idx) -> activation`` per stage.
      batch: (num_microbatches, mb, ...) microbatched inputs.
      stage_params: per-stage local params (pp-stacked, sharded).
      loss_fn: ``(last_stage_output, mb_idx) -> scalar`` per microbatch;
        evaluated on the last stage, mean-reduced over microbatches.

    Returns:
      (loss, grads) with loss replicated across stages and grads local to
      each stage's params — the reference returns per-stage losses and
      leaves grads in ``param.grad`` similarly.
    """
    axis = axis_name or _axis()
    pp = parallel_state.get_pipeline_model_parallel_world_size()

    def pipeline_loss(params):
        outs = spmd_pipeline(
            forward_step_fn, params, batch,
            num_microbatches=num_microbatches, remat=remat, axis_name=axis,
        )
        per_mb = jax.vmap(loss_fn)(outs, jnp.arange(num_microbatches))
        local = jnp.mean(per_mb)
        stage = jax.lax.axis_index(axis)
        # only the last stage's loss is real; zero others then sum
        return jax.lax.psum(jnp.where(stage == pp - 1, local, 0.0), axis)

    loss, grads = jax.value_and_grad(pipeline_loss)(stage_params)
    return loss, grads


def get_forward_backward_func(virtual_pipeline_model_parallel_size=None,
                              pipeline_model_parallel_size=None):
    """Reference dispatcher: interleaved scheduling is delegated to XLA's
    scheduler here, so both cases map to the same SPMD pipeline."""
    return forward_backward_pipelining_without_interleaving
