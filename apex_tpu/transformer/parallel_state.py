"""Model-parallel process-group bookkeeping on a named device mesh.

Rebuild of ``apex/transformer/parallel_state.py`` (SURVEY.md §2.4): the
reference builds NCCL groups (`_TENSOR_MODEL_PARALLEL_GROUP`,
`_PIPELINE_MODEL_PARALLEL_GROUP`, `_DATA_PARALLEL_GROUP`, embedding
groups) from a flat world. On TPU the same bookkeeping is a
``jax.sharding.Mesh`` with named axes:

    mesh axes (outer→inner): ("pipeline", "data", "tensor")

Tensor-parallel is innermost so TP collectives ride nearest-neighbor ICI
links; pipeline is outermost so PP hops can cross DCN on multi-slice
topologies (the reference has no topology awareness at all — SURVEY.md
§2.4 — so this is a strict upgrade).

Rank getters come in two flavors: static sizes (usable anywhere) and
in-context ranks (``*_rank()``), which require a bound axis (inside
``shard_map`` over the mesh) and return traced values, mirroring how the
reference's rank queries require an initialized process group.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

_MESH: Optional[Mesh] = None
_TP_SIZE = 1
_PP_SIZE = 1
_DP_SIZE = 1
_VIRTUAL_PP_SIZE: Optional[int] = None

TENSOR_AXIS = "tensor"
PIPELINE_AXIS = "pipeline"
DATA_AXIS = "data"


def initialize_model_parallel(
    tensor_model_parallel_size_: int = 1,
    pipeline_model_parallel_size_: int = 1,
    virtual_pipeline_model_parallel_size_: Optional[int] = None,
    pipeline_model_parallel_split_rank_: Optional[int] = None,
    *,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build and install the global mesh (reference:
    ``initialize_model_parallel``). Data-parallel size is inferred as
    ``world // (tp * pp)``, exactly like the reference."""
    global _MESH, _TP_SIZE, _PP_SIZE, _DP_SIZE, _VIRTUAL_PP_SIZE

    devices = list(devices if devices is not None else jax.devices())
    world = len(devices)
    tp = int(tensor_model_parallel_size_)
    pp = int(pipeline_model_parallel_size_)
    if world % (tp * pp) != 0:
        raise RuntimeError(
            f"world size ({world}) is not divisible by tensor parallel size "
            f"({tp}) times pipeline parallel size ({pp})"
        )
    dp = world // (tp * pp)
    dev_array = np.asarray(devices).reshape(pp, dp, tp)
    _MESH = Mesh(dev_array, (PIPELINE_AXIS, DATA_AXIS, TENSOR_AXIS))
    _TP_SIZE, _PP_SIZE, _DP_SIZE = tp, pp, dp
    _VIRTUAL_PP_SIZE = virtual_pipeline_model_parallel_size_
    return _MESH


def model_parallel_is_initialized() -> bool:
    return _MESH is not None


def destroy_model_parallel():
    global _MESH, _TP_SIZE, _PP_SIZE, _DP_SIZE, _VIRTUAL_PP_SIZE
    _MESH = None
    _TP_SIZE = _PP_SIZE = _DP_SIZE = 1
    _VIRTUAL_PP_SIZE = None


def get_mesh() -> Mesh:
    if _MESH is None:
        raise RuntimeError("model parallel mesh is not initialized")
    return _MESH


# -- group handles (axis names stand in for process groups) ----------------

def get_tensor_model_parallel_group() -> str:
    return TENSOR_AXIS


def get_pipeline_model_parallel_group() -> str:
    return PIPELINE_AXIS


def get_data_parallel_group() -> str:
    return DATA_AXIS


# -- static sizes ----------------------------------------------------------

def get_tensor_model_parallel_world_size() -> int:
    return _TP_SIZE


def get_pipeline_model_parallel_world_size() -> int:
    return _PP_SIZE


def get_data_parallel_world_size() -> int:
    return _DP_SIZE


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _VIRTUAL_PP_SIZE


# -- in-context (traced) ranks --------------------------------------------

def get_tensor_model_parallel_rank():
    """Traced TP rank; requires a bound ``tensor`` axis (inside shard_map)."""
    return jax.lax.axis_index(TENSOR_AXIS)


def get_pipeline_model_parallel_rank():
    return jax.lax.axis_index(PIPELINE_AXIS)


def get_data_parallel_rank():
    return jax.lax.axis_index(DATA_AXIS)


def is_pipeline_first_stage(ignore_virtual: bool = True):
    return get_pipeline_model_parallel_rank() == 0


def is_pipeline_last_stage(ignore_virtual: bool = True):
    return get_pipeline_model_parallel_rank() == _PP_SIZE - 1


# vocab range helper used by VocabParallelEmbedding / parallel CE
class VocabUtility:
    """Reference: ``tensor_parallel/utils.py:VocabUtility``."""

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(per_partition_vocab_size, rank):
        start = rank * per_partition_vocab_size
        return start, start + per_partition_vocab_size

    @staticmethod
    def vocab_range_from_global_vocab_size(global_vocab_size, rank, world_size):
        if global_vocab_size % world_size != 0:
            raise ValueError(
                f"vocab size ({global_vocab_size}) must be divisible by "
                f"tensor parallel size ({world_size})"
            )
        per = global_vocab_size // world_size
        return VocabUtility.vocab_range_from_per_partition_vocab_size(per, rank)
