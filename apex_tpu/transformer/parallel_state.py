"""Model-parallel process-group bookkeeping on a named device mesh.

Rebuild of ``apex/transformer/parallel_state.py`` (SURVEY.md §2.4): the
reference builds NCCL groups (`_TENSOR_MODEL_PARALLEL_GROUP`,
`_PIPELINE_MODEL_PARALLEL_GROUP`, `_DATA_PARALLEL_GROUP`, embedding
groups) from a flat world. On TPU the same bookkeeping is a
``jax.sharding.Mesh`` with named axes:

    mesh axes (outer→inner): ("pipeline", "data", "tensor")

Tensor-parallel is innermost so TP collectives ride nearest-neighbor ICI
links; pipeline is outermost so PP hops can cross DCN on multi-slice
topologies (the reference has no topology awareness at all — SURVEY.md
§2.4 — so this is a strict upgrade).

Rank getters come in two flavors: static sizes (usable anywhere) and
in-context ranks (``*_rank()``), which require a bound axis (inside
``shard_map`` over the mesh) and return traced values, mirroring how the
reference's rank queries require an initialized process group.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

_MESH: Optional[Mesh] = None
_TP_SIZE = 1
_PP_SIZE = 1
_DP_SIZE = 1
_EP_SIZE = 1
_VIRTUAL_PP_SIZE: Optional[int] = None
_DCN_DP_SIZE = 1
_DCN_PP_SIZE = 1
_NUM_SLICES = 1

TENSOR_AXIS = "tensor"
PIPELINE_AXIS = "pipeline"
DATA_AXIS = "data"
EXPERT_AXIS = "expert"


def _slice_of(device, position, world, num_slices):
    """Slice id of a device: the hardware's ``slice_index`` when the
    runtime exposes one (real multi-slice TPU), else contiguous
    partitioning by POSITION in the supplied device list — not by
    ``device.id``, which need not be dense 0..world-1 when the caller
    passes an arbitrary subset (e.g. a tail slice of ``jax.devices()``)."""
    idx = getattr(device, "slice_index", None)
    if idx is not None:
        return int(idx)
    return position * num_slices // world


def initialize_model_parallel(
    tensor_model_parallel_size_: int = 1,
    pipeline_model_parallel_size_: int = 1,
    virtual_pipeline_model_parallel_size_: Optional[int] = None,
    pipeline_model_parallel_split_rank_: Optional[int] = None,
    expert_model_parallel_size_: int = 1,
    *,
    dcn_data_parallel_size_: int = 1,
    dcn_pipeline_model_parallel_size_: int = 1,
    num_slices: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build and install the global mesh (reference:
    ``initialize_model_parallel``). Data-parallel size is inferred as
    ``world // (tp * pp * ep)``, exactly like the reference (which has no
    ep; with the default ``expert_model_parallel_size_=1`` the mesh
    degenerates to the reference's tp/pp/dp factorization — the expert
    axis still exists but has size 1, so every spec and collective that
    names it is a no-op).

    Expert parallelism follows the Megatron-LM convention: ep is carved
    out of the data-parallel group, so non-expert parameters are
    replicated over (data, expert) while expert parameters are replicated
    over data only and SHARDED over expert. Gradient sync therefore uses
    :func:`get_data_parallel_group` (→ ``("data", "expert")``) for dense
    params and :func:`get_expert_data_parallel_group` (→ ``"data"``) for
    expert params.

    Multi-slice (DCN) hierarchy (SURVEY.md §2.4 "DCN on outermost axis"):
    ``dcn_data_parallel_size_`` / ``dcn_pipeline_model_parallel_size_``
    factor dp and pp into (DCN outer × ICI inner). Devices are grouped by
    slice (hardware ``slice_index``, or contiguous partitioning for the
    CPU-sim dryrun via ``num_slices``) and laid out so the OUTERMOST
    positions of the pipeline/data axes cross slices while tp/ep (and the
    inner dp/pp factors) stay inside one slice — TP collectives ride ICI;
    only gradient allreduce / pipeline-boundary hops cross DCN. The axis
    names are unchanged, so every consumer (TP layers, DDP, schedules)
    works identically on flat and hybrid meshes."""
    global _MESH, _TP_SIZE, _PP_SIZE, _DP_SIZE, _EP_SIZE, _VIRTUAL_PP_SIZE
    global _DCN_DP_SIZE, _DCN_PP_SIZE, _NUM_SLICES

    devices = list(devices if devices is not None else jax.devices())
    world = len(devices)
    tp = int(tensor_model_parallel_size_)
    pp = int(pipeline_model_parallel_size_)
    ep = int(expert_model_parallel_size_)
    dcn_dp = int(dcn_data_parallel_size_)
    dcn_pp = int(dcn_pipeline_model_parallel_size_)
    if world % (tp * pp * ep) != 0:
        raise RuntimeError(
            f"world size ({world}) is not divisible by tensor parallel size "
            f"({tp}) times pipeline parallel size ({pp}) times expert "
            f"parallel size ({ep})"
        )
    dp = world // (tp * pp * ep)

    n_slices = (int(num_slices) if num_slices is not None
                else dcn_dp * dcn_pp)
    if dcn_dp * dcn_pp != n_slices:
        raise RuntimeError(
            f"dcn_data_parallel_size_ ({dcn_dp}) * "
            f"dcn_pipeline_model_parallel_size_ ({dcn_pp}) must equal the "
            f"slice count ({n_slices})")
    if dp % dcn_dp or pp % dcn_pp:
        raise RuntimeError(
            f"dp ({dp}) / pp ({pp}) must be divisible by their DCN "
            f"factors ({dcn_dp} / {dcn_pp})")
    if world % n_slices:
        raise RuntimeError(
            f"world size ({world}) is not divisible by the slice count "
            f"({n_slices})")

    if n_slices == 1:
        dev_array = np.asarray(devices).reshape(pp, dp, ep, tp)
    else:
        per_slice = world // n_slices
        ici_pp, ici_dp = pp // dcn_pp, dp // dcn_dp
        if ici_pp * ici_dp * ep * tp != per_slice:
            raise RuntimeError(
                f"per-slice device count ({per_slice}) != ici_pp * ici_dp "
                f"* ep * tp ({ici_pp}*{ici_dp}*{ep}*{tp})")
        groups = [[] for _ in range(n_slices)]
        for pos, d in enumerate(devices):
            groups[_slice_of(d, pos, world, n_slices)].append(d)
        if any(len(g) != per_slice for g in groups):
            raise RuntimeError(
                f"uneven slices: {[len(g) for g in groups]} (expected "
                f"{per_slice} devices per slice)")
        dev_array = np.empty((pp, dp, ep, tp), dtype=object)
        for s, g in enumerate(groups):
            sp, sd = divmod(s, dcn_dp)   # slice coords on (dcn_pp, dcn_dp)
            block = np.asarray(g).reshape(ici_pp, ici_dp, ep, tp)
            dev_array[sp * ici_pp:(sp + 1) * ici_pp,
                      sd * ici_dp:(sd + 1) * ici_dp] = block
    _MESH = Mesh(dev_array, (PIPELINE_AXIS, DATA_AXIS, EXPERT_AXIS,
                             TENSOR_AXIS))
    _TP_SIZE, _PP_SIZE, _DP_SIZE, _EP_SIZE = tp, pp, dp, ep
    _VIRTUAL_PP_SIZE = virtual_pipeline_model_parallel_size_
    _DCN_DP_SIZE, _DCN_PP_SIZE, _NUM_SLICES = dcn_dp, dcn_pp, n_slices
    return _MESH


def model_parallel_is_initialized() -> bool:
    return _MESH is not None


def destroy_model_parallel():
    global _MESH, _TP_SIZE, _PP_SIZE, _DP_SIZE, _EP_SIZE, _VIRTUAL_PP_SIZE
    global _DCN_DP_SIZE, _DCN_PP_SIZE, _NUM_SLICES
    _MESH = None
    _TP_SIZE = _PP_SIZE = _DP_SIZE = _EP_SIZE = 1
    _VIRTUAL_PP_SIZE = None
    _DCN_DP_SIZE = _DCN_PP_SIZE = _NUM_SLICES = 1


def get_mesh() -> Mesh:
    if _MESH is None:
        raise RuntimeError("model parallel mesh is not initialized")
    return _MESH


# -- group handles (axis names stand in for process groups) ----------------

def get_tensor_model_parallel_group() -> str:
    return TENSOR_AXIS


def get_pipeline_model_parallel_group() -> str:
    return PIPELINE_AXIS


def get_data_parallel_group():
    """Axis name(s) for full data-parallel gradient sync of DENSE (non-
    expert) params. With ep>1 this is the ("data", "expert") axis pair —
    dense params are replicated over both — and jax collectives accept
    the tuple directly."""
    if _EP_SIZE > 1:
        return (DATA_AXIS, EXPERT_AXIS)
    return DATA_AXIS


def get_expert_model_parallel_group() -> str:
    return EXPERT_AXIS


def get_expert_data_parallel_group() -> str:
    """Axis for gradient sync of EXPERT params (which are sharded over
    ``expert``, replicated over ``data`` only)."""
    return DATA_AXIS


# -- static sizes ----------------------------------------------------------

def get_tensor_model_parallel_world_size() -> int:
    return _TP_SIZE


def get_pipeline_model_parallel_world_size() -> int:
    return _PP_SIZE


def get_data_parallel_world_size() -> int:
    """Size of the FULL data-parallel replica group — ``world //
    (tp * pp)``, matching the reference and pairing with
    :func:`get_data_parallel_group` (with ep>1 that group is the
    ("data", "expert") axis pair, so this is ``dp * ep``; the raw
    ``data`` mesh-axis size is :func:`get_expert_data_parallel_world_size`)."""
    return _DP_SIZE * _EP_SIZE


def get_expert_data_parallel_world_size() -> int:
    """Size of the ``data`` mesh axis alone — the replica group of
    EXPERT params (pairs with :func:`get_expert_data_parallel_group`)."""
    return _DP_SIZE


def get_expert_model_parallel_world_size() -> int:
    return _EP_SIZE


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _VIRTUAL_PP_SIZE


def get_num_slices() -> int:
    """Slice count of the hybrid ICI×DCN mesh (1 = flat single-slice)."""
    return _NUM_SLICES


def get_dcn_data_parallel_world_size() -> int:
    """DCN (outer, cross-slice) factor of the data-parallel axis."""
    return _DCN_DP_SIZE


def get_dcn_pipeline_model_parallel_world_size() -> int:
    """DCN (outer, cross-slice) factor of the pipeline axis."""
    return _DCN_PP_SIZE


def get_ici_data_parallel_world_size() -> int:
    """ICI (inner, intra-slice) factor of the data-parallel axis."""
    return _DP_SIZE // _DCN_DP_SIZE


def get_ici_pipeline_model_parallel_world_size() -> int:
    """ICI (inner, intra-slice) factor of the pipeline axis."""
    return _PP_SIZE // _DCN_PP_SIZE


# -- in-context (traced) ranks --------------------------------------------

def get_tensor_model_parallel_rank():
    """Traced TP rank; requires a bound ``tensor`` axis (inside shard_map)."""
    return jax.lax.axis_index(TENSOR_AXIS)


def get_pipeline_model_parallel_rank():
    return jax.lax.axis_index(PIPELINE_AXIS)


def get_data_parallel_rank():
    return jax.lax.axis_index(DATA_AXIS)


def get_expert_model_parallel_rank():
    """Traced EP rank; requires a bound ``expert`` axis."""
    return jax.lax.axis_index(EXPERT_AXIS)


def is_pipeline_first_stage(ignore_virtual: bool = True):
    return get_pipeline_model_parallel_rank() == 0


def is_pipeline_last_stage(ignore_virtual: bool = True):
    return get_pipeline_model_parallel_rank() == _PP_SIZE - 1


# The vocab-range helper lives in tensor_parallel.utils (VocabUtility),
# mirroring the reference layout.
