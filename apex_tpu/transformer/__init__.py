"""apex_tpu.transformer — Megatron-style model parallelism on a named
device mesh (SURVEY.md §2.3: TP + PP + Megatron-SP, rebuild of
``apex.transformer``)."""

from apex_tpu.transformer import enums  # noqa: F401
from apex_tpu.transformer import parallel_state  # noqa: F401
from apex_tpu.transformer import tensor_parallel  # noqa: F401
from apex_tpu.transformer import pipeline_parallel  # noqa: F401
from apex_tpu.transformer import functional  # noqa: F401
from apex_tpu.transformer import microbatches  # noqa: F401
from apex_tpu.transformer import moe  # noqa: F401
from apex_tpu.transformer.moe import MoEMLP, route_top_k  # noqa: F401
