"""FusedScaleMaskSoftmax — the attention-softmax dispatcher.

Rebuild of ``apex/transformer/functional/fused_softmax.py`` (SURVEY.md
§2.1): selects between the fused kernels and the composed fallback, with
the reference's knob surface (``input_in_fp16/bf16``,
``attn_mask_type`` causal/padding, ``scaled_masked_softmax_fusion``,
``mask_func``, ``softmax_in_fp32``, ``scale``). The CUDA kernels' shape
eligibility gate (``is_kernel_available``: 16 < sk <= 16384, pow-2-ish)
does not constrain the Pallas kernels, so fusion is available whenever
enabled.
"""

from __future__ import annotations

import enum
from typing import Callable, Optional

import jax.numpy as jnp

from apex_tpu.ops.softmax import (
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
    softmax_reference,
)


class AttnMaskType(enum.Enum):
    padding = 1
    causal = 2


class FusedScaleMaskSoftmax:
    """Callable mirroring the reference module's constructor/forward."""

    def __init__(
        self,
        input_in_fp16: bool = False,
        input_in_bf16: bool = True,
        attn_mask_type: AttnMaskType = AttnMaskType.padding,
        scaled_masked_softmax_fusion: bool = True,
        mask_func: Optional[Callable] = None,
        softmax_in_fp32: bool = True,
        scale: Optional[float] = None,
    ):
        if input_in_fp16 and input_in_bf16:
            raise RuntimeError("both fp16 and bf16 flags cannot be active at the same time.")
        if scale is not None and not softmax_in_fp32:
            raise RuntimeError("softmax should be in fp32 when scaled")
        self.input_in_fp16 = input_in_fp16
        self.input_in_bf16 = input_in_bf16
        self.attn_mask_type = attn_mask_type
        self.scaled_masked_softmax_fusion = scaled_masked_softmax_fusion
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale

    def is_kernel_available(self, mask, b, np_, sq, sk) -> bool:
        """The CUDA gate checked seq-len/pow2 limits; Pallas has none."""
        return self.scaled_masked_softmax_fusion

    def __call__(self, x, mask=None):
        scale = self.scale if self.scale is not None else 1.0
        sq, sk = (x.shape[-2], x.shape[-1]) if x.ndim >= 2 else (1, x.shape[-1])
        b = x.size // (sq * sk)
        np_ = x.shape[-3] if x.ndim >= 3 else 1
        if self.is_kernel_available(mask, b, np_, sq, sk):
            if self.attn_mask_type == AttnMaskType.causal:
                if mask is not None:
                    # the reference asserts mask is None here; combining the
                    # padding mask with the in-kernel causal mask is strictly
                    # more useful and keeps fused/fallback outputs identical
                    return scaled_masked_softmax(x, mask, scale, causal=True)
                return scaled_upper_triang_masked_softmax(x, scale)
            if mask is not None:
                return scaled_masked_softmax(x, mask, scale)
            return scaled_softmax(x, scale)
        # composed fallback (reference: forward_torch_softmax)
        xf = x.astype(jnp.float32) if self.softmax_in_fp32 else x
        if self.mask_func is not None and mask is not None:
            xf = self.mask_func(xf, mask)
        out = softmax_reference(
            xf, mask if self.mask_func is None else None, scale,
            causal=(self.attn_mask_type == AttnMaskType.causal),
        )
        return out.astype(x.dtype)
