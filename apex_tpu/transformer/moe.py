"""Mixture-of-experts layer with expert parallelism.

The reference (apex) predates MoE and has no expert subsystem; this
module extends the Megatron-style transformer tier
(``apex/transformer/`` (U), SURVEY.md §2.3) with the one parallelism
axis the reference lacks, designed TPU-first:

- **Static-capacity routing** (Switch/GShard style): every expert
  processes exactly ``capacity`` token slots per step, so all shapes are
  static and XLA can tile every matmul onto the MXU. Overflow tokens are
  dropped (their combine weight is zero, the residual stream carries
  them through), underflow slots are zero-padded — the standard TPU
  trade against dynamic gather/scatter, which Mosaic cannot lower and
  XLA cannot tile.
- **Dispatch/combine as one-hot einsums**: token→slot routing is a
  (T, E, C) 0/1 tensor contracted on the MXU, not a scatter.
- **Expert parallelism over the ``expert`` mesh axis**
  (:data:`apex_tpu.transformer.parallel_state.EXPERT_AXIS`):
  ``jax.lax.all_to_all`` exchanges token slots so each rank computes only
  its local experts; with ``ep == 1`` no collective is emitted and the
  layer runs unchanged on a single device.
- **fp32 router**: gate logits/softmax/losses in float32 regardless of
  activation dtype (bf16 routing is known to destabilize training).

Losses follow the Switch Transformer recipe: ``aux_loss`` is the
load-balance term ``E * mean(fraction_dispatched * mean_gate_prob)``
(minimized at uniform routing, where it equals 1), ``z_loss`` is
``mean(logsumexp(logits)^2)`` to keep router logits from drifting.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state
from apex_tpu.utils.collectives import axis_is_bound, mark_varying


class RouterOutput(NamedTuple):
    """Routing decision for one batch of tokens.

    dispatch: (T, E, C) 0/1 — token t goes to slot c of expert e.
    combine:  (T, E, C) fp32 — dispatch scaled by the gate probability.
    aux_loss: scalar load-balance loss (Switch Transformer eq. 4-6).
    z_loss:   scalar router z-loss.
    """

    dispatch: jax.Array
    combine: jax.Array
    aux_loss: jax.Array
    z_loss: jax.Array


def route_top_k(logits, k: int, capacity: int) -> RouterOutput:
    """Top-k static-capacity routing (GShard order: the k-th choices of
    all tokens queue behind every token's (k-1)-th choice, so a token's
    primary expert is only dropped if the expert is full of primaries).

    logits: (T, E) fp32 router scores. Returns :class:`RouterOutput`.
    """
    T, E = logits.shape
    if k > E:
        raise ValueError(f"top-k ({k}) exceeds number of experts ({E}): "
                         "later rounds would re-dispatch expert 0")
    logits = logits.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)

    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    remaining = probs
    used = jnp.zeros((T, E), jnp.float32)  # experts already chosen per token
    fill = jnp.zeros((E,), jnp.float32)    # slots already taken per expert
    frac_dispatched = jnp.zeros((E,), jnp.float32)

    for _ in range(k):
        choice = jnp.argmax(remaining, axis=-1)            # (T,)
        mask = jax.nn.one_hot(choice, E, dtype=jnp.float32)
        gate = jnp.sum(probs * mask, axis=-1)              # (T,)
        # arrival order within the expert, offset by earlier rounds' fill
        order = jnp.cumsum(mask, axis=0) * mask            # 1-based
        position = order + fill[None, :] * mask - 1.0
        keep = (position < capacity) & (mask > 0)
        position = jnp.where(keep, position, 0).astype(jnp.int32)
        keepf = keep.astype(jnp.float32)                   # (T, E)
        slot = jax.nn.one_hot(position, capacity, dtype=jnp.float32)
        contrib = mask[:, :, None] * keepf[:, :, None] * slot
        dispatch = dispatch + contrib
        combine = combine + contrib * gate[:, None, None]
        frac_dispatched = frac_dispatched + jnp.sum(mask, axis=0) / T
        fill = fill + jnp.sum(mask * keepf, axis=0)
        used = used + mask
        remaining = jnp.where(used > 0, -jnp.inf, remaining)

    # Switch load-balance loss over the PRIMARY assignment distribution
    mean_prob = jnp.mean(probs, axis=0)                    # (E,)
    aux_loss = E * jnp.sum((frac_dispatched / k) * mean_prob)
    z = jax.nn.logsumexp(logits, axis=-1)
    return RouterOutput(dispatch, combine, aux_loss, jnp.mean(z * z))


class MoEMLP(nn.Module):
    """Mixture-of-experts MLP block (drop-in for a dense transformer MLP).

    ``num_experts`` is the GLOBAL expert count; with expert parallelism
    each rank holds ``num_experts // ep`` experts, initialized from a
    rank-folded key (experts are decorrelated across ranks by design —
    unlike TP shards, expert weights are independent parameters, not
    slices of a master matrix). Token slots travel between ranks via
    ``all_to_all`` over :data:`parallel_state.EXPERT_AXIS`.

    Composes with tensor parallelism (Megatron TPxEP): when the mesh has
    ``tp > 1``, each expert's FFN is additionally column/row-split over
    the ``tensor`` axis (master-weight init: the full per-expert matrix
    from the shared key, tp rank slices its shard) and the row-parallel
    partials are psum'd. Input tokens must then be REPLICATED over the
    tensor axis (the usual Megatron placement: MoE sits where activations
    are tp-replicated; compose with SP gather/scatter outside if used).

    Expert-parallel gradient flow: expert params are varying over the
    ``expert`` (and, with tp>1, ``tensor``) axes; their cotangents stay
    per-rank (no sync needed beyond ``data``-axis DP, see
    :func:`parallel_state.get_expert_data_parallel_group`).
    """

    hidden_size: int
    ffn_hidden_size: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    activation: Callable = nn.gelu
    router_jitter: float = 0.0
    dtype: jnp.dtype = jnp.bfloat16
    params_dtype: jnp.dtype = jnp.float32
    # tp>1 only: False skips materializing the full per-expert matrix at
    # init (same escape hatch as tensor_parallel.layers for weights too
    # large per rank). Variance-correct either way here: the init scales
    # by the FULL fan-in explicitly, not shard shape.
    master_weight_init: bool = True

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        """x: (..., hidden) -> (y, aux_loss, z_loss). Flattens leading
        dims to a token axis internally."""
        ep = parallel_state.get_expert_model_parallel_world_size()
        # Abstract tracing outside shard_map (eval_shape for spec trees):
        # the expert axis is unbound, so skip collectives/rank folding —
        # every op in the skipped set is shape-preserving, so derived
        # shapes stay correct.
        bound = ep == 1 or axis_is_bound(parallel_state.EXPERT_AXIS)
        E, H, F = self.num_experts, self.hidden_size, self.ffn_hidden_size
        if E % ep != 0:
            raise ValueError(
                f"num_experts ({E}) not divisible by expert parallel size "
                f"({ep})")
        if self.top_k > E:
            raise ValueError(
                f"top_k ({self.top_k}) exceeds num_experts ({E})")
        e_local = E // ep

        lead = x.shape[:-1]
        tokens = x.reshape(-1, H)
        T = tokens.shape[0]
        capacity = max(1, int(-(-self.top_k * T * self.capacity_factor
                                // E)))  # ceil, static

        # --- router (fp32, replicated over the expert axis) ---
        wr = self.param("router", nn.initializers.normal(stddev=0.02),
                        (H, E), self.params_dtype)
        logits = tokens.astype(jnp.float32) @ wr.astype(jnp.float32)
        if self.router_jitter and not deterministic:
            key = self.make_rng("dropout")
            logits = logits * jax.random.uniform(
                key, logits.shape, jnp.float32,
                1.0 - self.router_jitter, 1.0 + self.router_jitter)
        routing = route_top_k(logits, self.top_k, capacity)

        # --- expert weights: e_local experts per rank (rank-folded key),
        # each expert's FFN optionally tensor-parallel: w1 column-split /
        # w2 row-split over the ``tensor`` axis (Megatron TPxEP grouped
        # GEMM), using the same master-weight init scheme as
        # tensor_parallel.layers — the full per-expert matrix is drawn
        # from the (ep-folded) key and the tp rank slices its shard, so
        # fan-in scaling sees the full matrix and the assembled weight is
        # independent of tp.
        tp = parallel_state.get_tensor_model_parallel_world_size()
        tp_bound = tp == 1 or axis_is_bound(parallel_state.TENSOR_AXIS)
        if F % tp != 0:
            raise ValueError(
                f"ffn_hidden_size ({F}) not divisible by tensor parallel "
                f"size ({tp})")
        f_local = F // tp

        def expert_init(slice_axis):
            # the same master-weight scheme as tensor_parallel.layers.
            # _master_init, inlined because the full fan-in (full[1]) is
            # known here even on the per-shard fallback path, which makes
            # master_weight_init=False variance-correct (unlike generic
            # fan-scaled initializers over a shard shape)
            def init(key, s, d):
                if ep > 1 and bound:
                    key = jax.random.fold_in(
                        key, parallel_state.get_expert_model_parallel_rank())
                full = list(s)
                full[slice_axis] = full[slice_axis] * tp
                scale = 1.0 / jnp.sqrt(full[1])  # FULL per-expert fan-in
                if tp == 1:
                    return jax.random.normal(key, tuple(full), d) * scale
                if not self.master_weight_init:
                    if tp_bound:
                        key = jax.random.fold_in(
                            key,
                            parallel_state.get_tensor_model_parallel_rank())
                    return jax.random.normal(key, s, d) * scale
                w = jax.random.normal(key, tuple(full), d) * scale
                starts = [0] * len(full)
                if tp_bound:
                    starts[slice_axis] = (
                        parallel_state.get_tensor_model_parallel_rank()
                        * s[slice_axis])
                return jax.lax.dynamic_slice(w, starts, s)
            return init

        w1 = self.param("w1", expert_init(2), (e_local, H, f_local),
                        self.params_dtype)
        b1 = self.param("b1", nn.initializers.zeros, (e_local, f_local),
                        self.params_dtype)
        w2 = self.param("w2", expert_init(1), (e_local, f_local, H),
                        self.params_dtype)
        b2 = self.param("b2", nn.initializers.zeros, (e_local, H),
                        self.params_dtype)
        if ep > 1 and bound:
            w1, b1, w2, b2 = mark_varying(
                (w1, b1, w2, b2), parallel_state.EXPERT_AXIS)
        if tp > 1 and tp_bound:
            w1, b1, w2 = mark_varying((w1, b1, w2),
                                      parallel_state.TENSOR_AXIS)

        def a2a(t):
            """all_to_all over the expert axis (identity when tracing
            outside shard_map — shape-preserving, so eval_shape-derived
            spec trees stay correct)."""
            if not bound:
                return t
            return jax.lax.all_to_all(t, parallel_state.EXPERT_AXIS,
                                      split_axis=0, concat_axis=0,
                                      tiled=False)

        # --- dispatch: (T, E, C) x (T, H) -> (E, C, H) on the MXU ---
        slots = jnp.einsum("tec,th->ech",
                           routing.dispatch.astype(self.dtype),
                           tokens.astype(self.dtype))
        if ep > 1:
            # (E, C, H) -> (ep, e_local, C, H); all_to_all swaps the ep
            # shard dim for the token-source dim: each rank ends up with
            # ITS experts' slots from ALL ep ranks.
            slots = a2a(slots.reshape(ep, e_local, capacity, H))
            # (ep_src, e_local, C, H) -> (e_local, ep_src*C, H): each local
            # expert batches its slots from every source rank
            slots = slots.transpose(1, 0, 2, 3).reshape(
                e_local, ep * capacity, H)

        # --- expert computation (batched over local experts; with tp>1
        # each rank computes its f_local slice and the row-parallel
        # partials are psum'd over the tensor axis, bias added once) ---
        h = jnp.einsum("ech,ehf->ecf", slots, w1.astype(self.dtype))
        h = self.activation(h + b1[:, None, :].astype(self.dtype))
        out = jnp.einsum("ecf,efh->ech", h, w2.astype(self.dtype))
        if tp > 1 and tp_bound:
            out = jax.lax.psum(out, parallel_state.TENSOR_AXIS)
        out = out + b2[:, None, :].astype(self.dtype)

        if ep > 1:
            # (e_local, ep_src*C, H) -> (ep_src, e_local, C, H), send each
            # source rank's slots home; after the exchange dim0 indexes the
            # expert's OWNER rank, so the flat view is global expert order.
            out = a2a(out.reshape(e_local, ep, capacity, H)
                      .transpose(1, 0, 2, 3))
            out = out.reshape(E, capacity, H)

        # --- combine: weighted un-dispatch back to token order ---
        y = jnp.einsum("ech,tec->th", out.astype(jnp.float32),
                       routing.combine)
        return (y.astype(self.dtype).reshape(*lead, H),
                routing.aux_loss, routing.z_loss)
