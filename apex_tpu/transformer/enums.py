"""Transformer enums (reference: ``apex/transformer/enums.py`` (U)).

The reference's Megatron-style call sites key layer construction and
softmax fusion on these enums; ``AttnMaskType`` is defined next to the
fused softmax it configures and re-exported here, the rest are the
structural selectors pipeline/model builders switch on.
"""

from __future__ import annotations

import enum

from apex_tpu.transformer.functional.fused_softmax import (  # noqa: F401
    AttnMaskType,
)


class ModelType(enum.Enum):
    encoder_or_decoder = 1
    encoder_and_decoder = 2


class LayerType(enum.Enum):
    encoder = 1
    decoder = 2


class AttnType(enum.Enum):
    self_attn = 1
    cross_attn = 2
