"""Microbatch-count calculators.

Rebuild of ``apex/transformer/microbatches.py`` (SURVEY.md §2.3 PP row):
the reference computes, from (global batch, micro batch, data-parallel
size), how many microbatches each pipeline pass runs — either a constant
or a linear batch-size rampup over consumed samples. The calculator is
process-global (set up once, read by the training loop), matching the
reference's ``setup_microbatch_calculator`` /
``get_num_microbatches()`` singleton surface.

These are host-side Python numbers (they select trace shapes — a
changed microbatch count retraces the step, which is also true of the
reference: it re-buckets the schedule loop).
"""

from __future__ import annotations

from typing import List, Optional, Union


class NumMicroBatchesCalculator:
    """Reference ABC surface: ``get()`` and ``update()``."""

    num_micro_batches: int
    current_global_batch_size: int

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def update(self, consumed_samples: int, consistency_check: bool):
        raise NotImplementedError


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    """Reference: ``ConstantNumMicroBatches`` — fixed global batch."""

    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 data_parallel_size: int):
        per_pass = micro_batch_size * data_parallel_size
        if global_batch_size % per_pass != 0:
            raise ValueError(
                f"global batch size ({global_batch_size}) is not divisible "
                f"by micro batch size ({micro_batch_size}) times data "
                f"parallel size ({data_parallel_size})")
        self.num_micro_batches = global_batch_size // per_pass
        if self.num_micro_batches < 1:
            raise ValueError("num_micro_batches must be >= 1")
        self.current_global_batch_size = global_batch_size

    def update(self, consumed_samples: int, consistency_check: bool):
        pass


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    """Reference: ``RampupBatchsizeNumMicroBatches`` — global batch grows
    linearly from ``start_batch_size`` to ``global_batch_size`` in
    ``batch_size_increment`` steps over ``ramup_samples`` samples."""

    def __init__(self, start_batch_size: int, batch_size_increment: int,
                 ramup_samples: int, global_batch_size: int,
                 micro_batch_size: int, data_parallel_size: int):
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.global_batch_size = global_batch_size
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.micro_batch_times_data_parallel = (
            micro_batch_size * data_parallel_size)

        if start_batch_size % self.micro_batch_times_data_parallel != 0:
            raise ValueError(
                "start batch size must be divisible by micro-batch size "
                "times data-parallel size")
        if batch_size_increment <= 0:
            raise ValueError(
                f"batch size increment must be positive, got "
                f"{batch_size_increment}")
        diff = global_batch_size - start_batch_size
        if diff < 0:
            raise ValueError(
                f"global batch size ({global_batch_size}) must be >= start "
                f"batch size ({start_batch_size})")
        if diff % batch_size_increment != 0:
            raise ValueError(
                f"expected global batch size interval ({diff}) to be "
                f"divisible by global batch size increment "
                f"({batch_size_increment})")
        num_increments = diff // batch_size_increment
        self.rampup_samples_per_increment = (
            self.ramup_samples / num_increments if num_increments else 0)
        self.update(0, False)

    def update(self, consumed_samples: int, consistency_check: bool):
        if consumed_samples > self.ramup_samples or \
                self.rampup_samples_per_increment == 0:
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples /
                        self.rampup_samples_per_increment)
            self.current_global_batch_size = (
                self.start_batch_size + steps * self.batch_size_increment)
            self.current_global_batch_size = min(
                self.current_global_batch_size, self.global_batch_size)
        if consistency_check and (
                self.current_global_batch_size %
                self.micro_batch_times_data_parallel != 0):
            raise ValueError(
                f"current global batch size "
                f"({self.current_global_batch_size}) is not divisible by "
                "micro-batch-size * data-parallel-size")
        # round down to a runnable microbatch count (reference behavior:
        # the rampup sizes are expected to be divisible; without the
        # check we floor)
        self.num_micro_batches = max(
            self.current_global_batch_size //
            self.micro_batch_times_data_parallel, 1)


_GLOBAL_NUM_MICROBATCHES_CALCULATOR: Optional[NumMicroBatchesCalculator] = None


def build_num_microbatches_calculator(
    rank: int,
    rampup_batch_size: Optional[List[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
) -> NumMicroBatchesCalculator:
    """Reference factory: ``rampup_batch_size`` is None (constant) or
    ``[start, increment, ramup_samples]``."""
    if rampup_batch_size is None:
        calc = ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size)
        if rank == 0:
            from apex_tpu.amp._amp_state import maybe_print

            maybe_print(
                f"setting number of micro-batches to constant {calc.get()}")
    else:
        if len(rampup_batch_size) != 3:
            raise ValueError(
                "expected the following format: --rampup-batch-size "
                "<start batch size> <batch size increment> "
                "<ramp-up samples>")
        calc = RampupBatchsizeNumMicroBatches(
            int(rampup_batch_size[0]), int(rampup_batch_size[1]),
            int(rampup_batch_size[2]), global_batch_size,
            micro_batch_size, data_parallel_size)
    return calc


def setup_microbatch_calculator(rank, rampup_batch_size, global_batch_size,
                                micro_batch_size, data_parallel_size):
    """Reference: installs the process-global calculator."""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size)
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR


def _get_calculator() -> NumMicroBatchesCalculator:
    if _GLOBAL_NUM_MICROBATCHES_CALCULATOR is None:
        raise RuntimeError(
            "microbatch calculator is not set up; call "
            "setup_microbatch_calculator() first")
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR


def get_num_microbatches() -> int:
    return _get_calculator().get()


def get_current_global_batch_size() -> int:
    return _get_calculator().get_current_global_batch_size()


def update_num_microbatches(consumed_samples: int,
                            consistency_check: bool = True):
    _get_calculator().update(consumed_samples, consistency_check)


def destroy_microbatch_calculator():
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
