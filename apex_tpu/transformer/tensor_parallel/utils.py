"""Tensor-parallel sizing helpers.

Rebuild of ``apex/transformer/tensor_parallel/utils.py`` (U) and the
``ensure_divisibility``/``divide`` helpers of ``apex/transformer/utils.py``
(U) — the small arithmetic surface Megatron-style code builds shard
shapes from. Kept dependency-free so both model code and tests can use
it; everything works with Python ints *or* traced rank values (the JAX
analog of the reference's ``torch.distributed.get_rank()`` ints).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp

__all__ = [
    "ensure_divisibility",
    "divide",
    "split_tensor_along_last_dim",
    "VocabUtility",
]


def ensure_divisibility(numerator: int, denominator: int) -> None:
    """Raise unless ``denominator`` divides ``numerator`` exactly."""
    if numerator % denominator != 0:
        raise ValueError(
            f"{numerator} is not divisible by {denominator}")


def divide(numerator: int, denominator: int) -> int:
    """Exact integer division (raises on remainder)."""
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_along_last_dim(
        tensor, num_partitions: int,
        contiguous_split_chunks: bool = False) -> Sequence:
    """Split a tensor into ``num_partitions`` equal chunks along its last
    dimension. ``contiguous_split_chunks`` is accepted for drop-in parity
    with reference call sites and ignored: XLA arrays have no
    stride/contiguity notion, so every chunk here is already
    "contiguous"."""
    last = tensor.shape[-1]
    divide(last, num_partitions)  # validates
    return jnp.split(tensor, num_partitions, axis=-1)


class VocabUtility:
    """Shard-range arithmetic for a vocab dimension partitioned over the
    tensor-parallel axis: ranges are [first, last) index pairs.

    Reference: ``apex.transformer.tensor_parallel.utils.VocabUtility`` —
    used by ``VocabParallelEmbedding`` and the vocab-parallel cross
    entropy to map global token ids onto a rank's local rows. ``rank``
    may be a Python int or a traced ``jax.lax.axis_index`` value.
    """

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(
            per_partition_vocab_size: int, rank, world_size: int
    ) -> Tuple:
        first = rank * per_partition_vocab_size
        return first, first + per_partition_vocab_size

    @staticmethod
    def vocab_range_from_global_vocab_size(
            global_vocab_size: int, rank, world_size: int
    ) -> Tuple:
        per = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per, rank, world_size)
