"""Model-parallel RNG management + activation checkpointing.

Rebuild of ``apex/transformer/tensor_parallel/random.py`` (SURVEY.md §2.3
/ §5): the reference maintains per-TP-rank CUDA RNG states
(``CudaRNGStatesTracker``) so dropout inside TP regions differs per rank
while non-TP regions agree, and a ``checkpoint()`` that replays them for
activation recompute.

JAX's counter-based PRNG makes both trivial and bitwise-reproducible:

- per-rank streams are ``fold_in(key, tp_rank)`` — no state capture;
- ``checkpoint`` is ``jax.checkpoint`` (rematerialization): the SAME key
  reaches the recomputed segment, so dropout masks replay exactly. The
  reference needs RNG state save/restore precisely because CUDA RNG is
  stateful; here determinism is structural.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state

_MODEL_PARALLEL_RNG_TRACKER_NAME = "model-parallel-rng"


def model_parallel_key(key):
    """A key decorrelated across TP ranks (dropout inside TP regions)."""
    return jax.random.fold_in(key, jax.lax.axis_index(parallel_state.TENSOR_AXIS))


class RNGStatesTracker:
    """API-parity port of ``CudaRNGStatesTracker``: named RNG streams.

    ``add(name, seed)`` registers a stream; ``fork(name)`` returns a fresh
    key from it (advancing a counter — the functional analog of forking
    the CUDA RNG state and restoring it afterwards).
    """

    def __init__(self):
        self.states_: Dict[str, jnp.ndarray] = {}
        self.counters_: Dict[str, int] = {}

    def reset(self):
        self.states_.clear()
        self.counters_.clear()

    def get_states(self):
        return dict(self.states_), dict(self.counters_)

    def set_states(self, states):
        self.states_, self.counters_ = dict(states[0]), dict(states[1])

    def add(self, name: str, seed: int):
        if name in self.states_:
            raise RuntimeError(f"rng state {name} already exists")
        self.states_[name] = jax.random.PRNGKey(seed)
        self.counters_[name] = 0

    def fork(self, name: str = _MODEL_PARALLEL_RNG_TRACKER_NAME):
        if name not in self.states_:
            raise RuntimeError(f"rng state {name} is not added")
        key = jax.random.fold_in(self.states_[name], self.counters_[name])
        self.counters_[name] += 1
        return key


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    """Reference name: ``get_cuda_rng_tracker``."""
    return _RNG_STATE_TRACKER


# torch-name alias for drop-in reading of ported code
get_cuda_rng_tracker = get_rng_state_tracker


def model_parallel_rng_seed(seed: int):
    """Reference: ``model_parallel_cuda_manual_seed`` — registers the
    model-parallel stream with a TP-rank offset baked in at fork time."""
    tracker = get_rng_state_tracker()
    tracker.reset()
    tracker.add(_MODEL_PARALLEL_RNG_TRACKER_NAME, seed)
    return tracker


model_parallel_cuda_manual_seed = model_parallel_rng_seed


def checkpoint(fn, *args, policy=None, prevent_cse: bool = True):
    """Activation checkpointing (reference: ``random.checkpoint``): run
    ``fn`` without saving intermediates; recompute them in backward.
    ``jax.checkpoint`` replays identical RNG keys, so dropout matches the
    forward bitwise — the property the reference's RNG fork/restore dance
    exists to guarantee."""
    return jax.checkpoint(fn, policy=policy, prevent_cse=prevent_cse)(*args)
