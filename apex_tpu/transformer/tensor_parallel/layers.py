"""Tensor-parallel layers: Column/Row-parallel linear, vocab-parallel
embedding.

Rebuild of ``apex/transformer/tensor_parallel/layers.py`` (SURVEY.md §2.3)
as flax modules holding LOCAL weight shards, for use inside ``shard_map``
over the ``tensor`` mesh axis. Knob parity: ``gather_output``,
``input_is_parallel``, ``skip_bias_add``, ``bias``,
``sequence_parallel_enabled``; ``gradient_accumulation_fusion`` is
accepted as documentation (XLA fuses the wgrad accumulation into the
backward dot — the very thing ``fused_weight_gradient_mlp_cuda`` exists
for, SURVEY.md §2.2). For cross-microbatch fp32 gradient accumulation
(the reference's ``main_grad`` buffers) use
:mod:`apex_tpu.transformer.tensor_parallel.main_grad`.

Weight partitioning matches the reference: ColumnParallelLinear splits the
output dim, RowParallelLinear the input dim, VocabParallelEmbedding the
vocab rows. Initialization follows the reference's
``_initialize_affine_weight`` master-weight scheme exactly: every rank
materializes the FULL weight from the SHARED key and dynamic-slices its
own shard, so fan-in/fan-out-scaled initializers (lecun/xavier) see the
full-matrix shape and the assembled weight is independent of tp. (A
per-shard init would inflate row-parallel stddev by sqrt(tp).) The full
matrix exists only transiently at init but IS materialized per rank
(the slice start is the traced rank index, so XLA cannot elide the
generation); for weights too large to materialize (huge vocab x hidden),
set ``master_weight_init=False`` to use a rank-folded per-shard init —
distributionally identical for scale-free initializers like
``normal(stddev)``, but NOT variance-correct for fan-scaled ones on
row-parallel layers.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel.utils import VocabUtility
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_along_first_dim,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_along_first_dim,
    scatter_to_tensor_model_parallel_region,
)

default_init = nn.initializers.lecun_normal()


def _master_init(init_method, key, full_shape, dtype, axis, num_shards,
                 shard_size, enabled: bool = True):
    """Reference ``_initialize_affine_weight``: init the full master weight
    from the shared key, then slice this rank's shard along ``axis``.

    Run per-rank inside ``shard_map``; the key is NOT rank-folded, so all
    ranks compute the identical master matrix and take disjoint slices —
    the assembled weight (and its variance) matches the single-device
    init bit-for-bit regardless of tp. With ``enabled=False`` (weights
    too large to materialize per rank) falls back to a rank-folded
    per-shard init."""
    if num_shards == 1:
        return init_method(key, full_shape, dtype)
    rank = jax.lax.axis_index(parallel_state.TENSOR_AXIS)
    if not enabled:
        shard_shape = list(full_shape)
        shard_shape[axis] = shard_size
        return init_method(jax.random.fold_in(key, rank),
                           tuple(shard_shape), dtype)
    full = init_method(key, full_shape, dtype)
    starts = [0] * len(full_shape)
    sizes = list(full_shape)
    starts[axis] = rank * shard_size
    sizes[axis] = shard_size
    return jax.lax.dynamic_slice(full, starts, sizes)


class ColumnParallelLinear(nn.Module):
    """Y = X A + b with A split along its output (column) dimension.

    Reference: ``ColumnParallelLinear``. Output is the local shard unless
    ``gather_output``. With ``sequence_parallel_enabled`` the input arrives
    sharded along dim 0 (sequence) and is all-gathered in forward /
    reduce-scattered in backward, per Megatron-SP.
    """

    input_size: int
    output_size: int
    bias: bool = True
    gather_output: bool = True
    skip_bias_add: bool = False
    sequence_parallel_enabled: bool = False
    gradient_accumulation_fusion: bool = False  # parity; XLA fuses wgrad
    init_method: Callable = default_init
    master_weight_init: bool = True
    params_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        tp = parallel_state.get_tensor_model_parallel_world_size()
        if self.output_size % tp != 0:
            raise ValueError(
                f"output_size ({self.output_size}) not divisible by tensor "
                f"parallel size ({tp})"
            )
        local_out = self.output_size // tp
        kernel = self.param(
            "kernel",
            lambda k, s, d: _master_init(
                self.init_method, k, (self.input_size, self.output_size),
                d, 1, tp, local_out, self.master_weight_init),
            (self.input_size, local_out),
            self.params_dtype,
        )
        if self.sequence_parallel_enabled:
            x = gather_along_first_dim(x)
        else:
            x = copy_to_tensor_model_parallel_region(x)
        y = jnp.matmul(x, kernel.astype(x.dtype))
        b = None
        if self.bias:
            b = self.param(
                "bias", nn.initializers.zeros, (local_out,), self.params_dtype
            )
            if not self.skip_bias_add:
                y = y + b.astype(y.dtype)
        if self.gather_output:
            if self.sequence_parallel_enabled:
                raise ValueError(
                    "gather_output is incompatible with sequence_parallel_enabled, "
                    "matching the reference assertion"
                )
            y = gather_from_tensor_model_parallel_region(y)
        if self.skip_bias_add:
            return y, b
        return y


class RowParallelLinear(nn.Module):
    """Y = X A + b with A split along its input (row) dimension.

    Reference: ``RowParallelLinear``. Input is the local shard when
    ``input_is_parallel`` (the usual case after a ColumnParallelLinear),
    else scattered here. The partial products are summed with an
    all-reduce — or a reduce-scatter along the sequence dim under
    ``sequence_parallel_enabled`` (Megatron-SP's decomposition). Bias is
    added AFTER the reduction (reference semantics: only once).
    """

    input_size: int
    output_size: int
    bias: bool = True
    input_is_parallel: bool = True
    skip_bias_add: bool = False
    sequence_parallel_enabled: bool = False
    gradient_accumulation_fusion: bool = False
    init_method: Callable = default_init
    master_weight_init: bool = True
    params_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        tp = parallel_state.get_tensor_model_parallel_world_size()
        if self.input_size % tp != 0:
            raise ValueError(
                f"input_size ({self.input_size}) not divisible by tensor "
                f"parallel size ({tp})"
            )
        local_in = self.input_size // tp
        kernel = self.param(
            "kernel",
            lambda k, s, d: _master_init(
                self.init_method, k, (self.input_size, self.output_size),
                d, 0, tp, local_in, self.master_weight_init),
            (local_in, self.output_size),
            self.params_dtype,
        )
        if not self.input_is_parallel:
            if self.sequence_parallel_enabled:
                raise ValueError(
                    "sequence_parallel_enabled requires input_is_parallel, "
                    "matching the reference assertion"
                )
            x = scatter_to_tensor_model_parallel_region(x)
        y = jnp.matmul(x, kernel.astype(x.dtype))
        if self.sequence_parallel_enabled:
            y = reduce_scatter_along_first_dim(y)
        else:
            y = reduce_from_tensor_model_parallel_region(y)
        b = None
        if self.bias:
            b = self.param(
                "bias", nn.initializers.zeros, (self.output_size,), self.params_dtype
            )
            if not self.skip_bias_add:
                y = y + b.astype(y.dtype)
        if self.skip_bias_add:
            return y, b
        return y


class VocabParallelEmbedding(nn.Module):
    """Embedding table split along the vocab dimension.

    Reference: ``VocabParallelEmbedding`` — out-of-range ids are masked to
    zero locally and the partial lookups are psum'd, so each id resolves on
    exactly one rank.
    """

    num_embeddings: int
    embedding_dim: int
    init_method: Callable = nn.initializers.normal(stddev=0.02)
    master_weight_init: bool = True
    params_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, ids):
        tp = parallel_state.get_tensor_model_parallel_world_size()
        rank = jax.lax.axis_index(parallel_state.TENSOR_AXIS)
        if self.num_embeddings % tp != 0:
            raise ValueError(
                f"num_embeddings ({self.num_embeddings}) not divisible by "
                f"tensor parallel size ({tp})"
            )
        per = self.num_embeddings // tp
        table = self.param(
            "embedding",
            lambda k, s, d: _master_init(
                self.init_method, k, (self.num_embeddings, self.embedding_dim),
                d, 0, tp, per, self.master_weight_init),
            (per, self.embedding_dim),
            self.params_dtype,
        )
        start, _ = VocabUtility.vocab_range_from_per_partition_vocab_size(
            per, rank, tp)
        local_ids = ids - start
        in_range = (local_ids >= 0) & (local_ids < per)
        safe_ids = jnp.where(in_range, local_ids, 0)
        out = jnp.take(table, safe_ids, axis=0)
        out = jnp.where(in_range[..., None], out, 0.0)
        return reduce_from_tensor_model_parallel_region(out)
