"""Vocab-parallel cross entropy.

Rebuild of ``apex/transformer/tensor_parallel/cross_entropy.py``
(SURVEY.md §2.3): softmax cross entropy over vocab-sharded logits without
ever materializing the full-vocab row. The reference's recipe is kept
exactly — local max → all-reduce(max), subtract, local sum-exp →
all-reduce(sum), local target-logit gather with out-of-range masking →
all-reduce(sum) — with the collectives as ``pmax``/``psum`` over the
``tensor`` axis, and a custom_vjp backward reproducing
(softmax - one_hot) on the local shard only.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel.utils import VocabUtility


def _axis():
    return parallel_state.TENSOR_AXIS


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def vocab_parallel_cross_entropy(vocab_parallel_logits, target, label_smoothing=0.0):
    """Per-token loss for vocab-sharded logits.

    Args:
      vocab_parallel_logits: (..., vocab/tp) local logits shard.
      target: (...) integer ids in [0, vocab).
    Returns:
      (...) per-token losses (replicated across the TP axis).
    """
    loss, _ = _ce_fwd_impl(vocab_parallel_logits, target, label_smoothing)
    return loss


def _ce_fwd_impl(logits, target, label_smoothing):
    tp = parallel_state.get_tensor_model_parallel_world_size()
    rank = jax.lax.axis_index(_axis())
    per = logits.shape[-1]
    vocab = per * tp

    lf = logits.astype(jnp.float32)
    local_max = jnp.max(lf, axis=-1)
    global_max = jax.lax.pmax(local_max, _axis())
    shifted = lf - global_max[..., None]
    exp = jnp.exp(shifted)
    local_sumexp = jnp.sum(exp, axis=-1)
    global_sumexp = jax.lax.psum(local_sumexp, _axis())

    start, _ = VocabUtility.vocab_range_from_per_partition_vocab_size(
        per, rank, tp)
    local_t = target - start
    in_range = (local_t >= 0) & (local_t < per)
    safe_t = jnp.where(in_range, local_t, 0)
    target_shifted = jnp.take_along_axis(shifted, safe_t[..., None], axis=-1)[..., 0]
    target_shifted = jnp.where(in_range, target_shifted, 0.0)
    target_shifted = jax.lax.psum(target_shifted, _axis())

    loss = jnp.log(global_sumexp) - target_shifted
    if label_smoothing > 0.0:
        # reference smoothing: mix in the mean of all log-probs
        # loss = (1-eps)*nll + eps * mean_i(-log p_i)
        log_probs = shifted - jnp.log(global_sumexp)[..., None]
        local_mean_term = jnp.sum(log_probs, axis=-1)
        global_mean = jax.lax.psum(local_mean_term, _axis()) / vocab
        loss = (1.0 - label_smoothing) * loss - label_smoothing * global_mean

    residuals = (exp, global_sumexp, in_range, safe_t, vocab)
    return loss, residuals


def _ce_fwd(logits, target, label_smoothing):
    loss, res = _ce_fwd_impl(logits, target, label_smoothing)
    # zero-size sentinel carries the primal dtype (residuals must be arrays)
    return loss, (res, jnp.zeros((0,), logits.dtype))


def _ce_bwd(label_smoothing, fwd_res, g):
    from apex_tpu.ops._common import match_vma

    (exp, global_sumexp, in_range, safe_t, vocab), dtype_sentinel = fwd_res
    dtype = dtype_sentinel.dtype
    softmax = exp / global_sumexp[..., None]
    one_hot = jax.nn.one_hot(safe_t, exp.shape[-1], dtype=jnp.float32)
    one_hot = one_hot * in_range[..., None]
    if label_smoothing > 0.0:
        grad = softmax - (1.0 - label_smoothing) * one_hot - label_smoothing / vocab
    else:
        grad = softmax - one_hot
    return match_vma((grad * g[..., None]).astype(dtype), exp), None


vocab_parallel_cross_entropy.defvjp(_ce_fwd, _ce_bwd)
