"""fp32 main-gradient accumulation (reference:
``fused_weight_gradient_mlp_cuda`` + the ``main_grad`` buffers its
``gradient_accumulation_fusion`` path writes into; SURVEY.md §2.2).

The reference's CUDA wgrad GEMM accumulates directly into an fp32
``param.main_grad`` buffer so that summing many bf16/fp16 microbatch
gradients never loses precision to the low-precision format. The
TPU-native equivalent is a functional fp32 accumulator pytree: the cast
+ add chain fuses into the backward dot's epilogue under XLA — the same
"wgrad writes fp32" data flow without a custom kernel.

Usage (gradient accumulation over microbatches)::

    main = init_main_grads(params)
    for micro in microbatches:
        grads = jax.grad(loss)(params, micro)     # bf16 grads
        main = accumulate_main_grads(main, grads) # fp32 accumulation
    params, opt_state = opt.step(main, opt_state, params)
    main = reset_main_grads(main)

The TP layers' ``gradient_accumulation_fusion`` knob documents this as
its implementation (``tensor_parallel/layers.py``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init_main_grads(params):
    """fp32 zero pytree shaped like ``params`` (the ``main_grad``
    buffers)."""
    return jax.tree.map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)


def accumulate_main_grads(main_grads, grads):
    """main += fp32(grads) — one fused cast+add pass per leaf."""
    return jax.tree.map(
        lambda m, g: m + g.astype(jnp.float32), main_grads, grads)


def reset_main_grads(main_grads):
    """Zero the accumulators (reference: ``zero_grad`` on main_grad)."""
    return jax.tree.map(jnp.zeros_like, main_grads)
