"""Tensor-parallel communication mappings.

Rebuild of ``apex/transformer/tensor_parallel/mappings.py`` (SURVEY.md
§2.3): the region mappings of Megatron TP plus the sequence-parallel
first-dim scatter/gather pair, over the ``tensor`` mesh axis inside
``shard_map``.

Design note — why there are no custom autograd functions here, unlike the
reference: the reference implements each mapping as an autograd Function
(``_CopyToModelParallelRegion`` etc.) because torch cannot know which
tensors are replicated vs. sharded across ranks. JAX shard_map tracks
exactly that (the aval's varying-axes set), and its autodiff provides the
correct transposes natively:

- ``copy``    = mark-varying (``pcast to='varying'``); transpose = psum —
  precisely the identity-fwd/allreduce-bwd pair.
- ``reduce``  = ``psum``; transpose = mark-varying (identity values).
- ``scatter`` = per-rank ``dynamic_slice``; transpose zero-pads the local
  chunk, and the boundary psum for replicated inputs reassembles the full
  gradient — the reference's all-gather backward.
- ``gather``  = ``all_gather``; transpose = reduce-scatter.

Hand-rolling the reference's backward collectives on top of this (as a
custom_vjp) would DOUBLE-apply the boundary psum for replicated inputs.
"""

from __future__ import annotations

import jax

from apex_tpu.transformer import parallel_state
from apex_tpu.utils.collectives import mark_varying


def _axis():
    return parallel_state.TENSOR_AXIS


def _mark_varying(x):
    return mark_varying(x, _axis())


def copy_to_tensor_model_parallel_region(x):
    """Identity forward, all-reduce backward (reference:
    ``_CopyToModelParallelRegion``) — the entry mapping of
    ColumnParallelLinear."""
    return _mark_varying(x)


def reduce_from_tensor_model_parallel_region(x):
    """All-reduce forward, identity backward (reference:
    ``_ReduceFromModelParallelRegion``) — the exit mapping of
    RowParallelLinear."""
    return jax.lax.psum(x, _axis())


def scatter_to_tensor_model_parallel_region(x):
    """Keep this rank's last-dim chunk (reference:
    ``_ScatterToModelParallelRegion``); backward reassembles the full
    gradient."""
    tp = parallel_state.get_tensor_model_parallel_world_size()
    rank = jax.lax.axis_index(_axis())
    chunk = x.shape[-1] // tp
    return jax.lax.dynamic_slice_in_dim(
        _mark_varying(x), rank * chunk, chunk, axis=x.ndim - 1
    )


def gather_from_tensor_model_parallel_region(x):
    """All-gather last-dim chunks (reference:
    ``_GatherFromModelParallelRegion``); backward keeps this rank's chunk
    (reduce-scatter transpose)."""
    return jax.lax.all_gather(x, _axis(), axis=x.ndim - 1, tiled=True)


# -- sequence-parallel first-dim pair (SURVEY.md §2.3 SP row) --------------

def reduce_scatter_along_first_dim(x):
    """reduce-scatter over the sequence dim (reference:
    ``_reduce_scatter_along_first_dim``) — SP's replacement for the
    RowParallel exit allreduce; backward all-gathers."""
    return jax.lax.psum_scatter(x, _axis(), scatter_dimension=0, tiled=True)


def gather_along_first_dim(x):
    """all-gather over the sequence dim (reference:
    ``_gather_along_first_dim``); backward reduce-scatters."""
    return jax.lax.all_gather(x, _axis(), axis=0, tiled=True)
