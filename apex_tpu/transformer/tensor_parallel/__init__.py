"""apex_tpu.transformer.tensor_parallel — Megatron-style TP (SURVEY.md §2.3)."""

from apex_tpu.transformer.tensor_parallel.cross_entropy import (  # noqa: F401
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import (  # noqa: F401
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from apex_tpu.transformer.tensor_parallel.mappings import (  # noqa: F401
    copy_to_tensor_model_parallel_region,
    gather_along_first_dim,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_along_first_dim,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.random import (  # noqa: F401
    RNGStatesTracker,
    checkpoint,
    get_cuda_rng_tracker,
    get_rng_state_tracker,
    model_parallel_cuda_manual_seed,
    model_parallel_key,
    model_parallel_rng_seed,
)
from apex_tpu.transformer.tensor_parallel.main_grad import (  # noqa: F401,E402
    accumulate_main_grads,
    init_main_grads,
    reset_main_grads,
)
from apex_tpu.transformer.tensor_parallel.utils import (  # noqa: F401,E402
    VocabUtility,
    divide,
    ensure_divisibility,
    split_tensor_along_last_dim,
)
