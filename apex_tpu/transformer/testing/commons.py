"""Shared helpers for parallelism tests and user experiments.

Rebuild of the reference's ``apex/transformer/testing/commons.py`` (U)
tier: deterministic seeding, tiny identity-ish modules, a toy MLP model,
and the model-parallel harness the reference builds from
``NcclDistributedTestBase`` (multi-process NCCL on one node). The TPU
analog is stronger — ``model_parallel_harness`` runs the caller's
function under ``shard_map`` on the current (possibly CPU-simulated)
mesh, so "distributed" tests need no accelerator at all (SURVEY.md §4).
"""

from __future__ import annotations

import collections
import contextlib
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state

__all__ = [
    "set_random_seed",
    "IdentityLayer",
    "ToyParallelMLP",
    "initialize_distributed",
    "model_parallel_harness",
    "print_separator",
]


def set_random_seed(seed: int):
    """Deterministic seeds for every RNG the tests touch (reference
    ``commons.set_random_seed``: python/numpy/torch/model-parallel
    trackers; here numpy + a returned JAX key — JAX keys are explicit,
    so the key IS the seeding)."""
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


class IdentityLayer(nn.Module):
    """A single learnable weight returned as-is (the reference's
    ``IdentityLayer``): the minimal differentiable module for exercising
    mappings/schedules without model noise."""

    shape: tuple
    scale: float = 1.0

    @nn.compact
    def __call__(self):
        w = self.param("weight", nn.initializers.normal(self.scale),
                       self.shape)
        return w


class ToyParallelMLP(nn.Module):
    """Column→Row parallel 2-layer MLP — the smallest model that drives
    the full TP mapping set (identity-fwd/psum-bwd, scatter/gather)."""

    hidden: int
    ffn: int

    @nn.compact
    def __call__(self, x):
        from apex_tpu.transformer.tensor_parallel import (
            ColumnParallelLinear,
            RowParallelLinear,
        )

        h = ColumnParallelLinear(input_size=self.hidden,
                                 output_size=self.ffn,
                                 gather_output=False, name="fc1")(x)
        h = jax.nn.gelu(h)
        return RowParallelLinear(input_size=self.ffn,
                                 output_size=self.hidden,
                                 input_is_parallel=True, name="fc2")(h)


def initialize_distributed(tensor_model_parallel_size: int = 1,
                           pipeline_model_parallel_size: int = 1,
                           **kw):
    """Reference ``initialize_distributed`` analog: bring up the named
    mesh (rather than a torch process group) and return it."""
    return parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=tensor_model_parallel_size,
        pipeline_model_parallel_size_=pipeline_model_parallel_size, **kw)


@contextlib.contextmanager
def model_parallel_harness(tensor_model_parallel_size: int = 1,
                           pipeline_model_parallel_size: int = 1, **kw):
    """Context manager that initializes model parallelism, yields a
    ``run(f, *args, in_specs=..., out_specs=...)`` callable executing
    ``f`` jitted under ``shard_map`` on the full mesh, and tears the
    mesh down afterwards — the role of the reference's
    ``NcclDistributedTestBase`` setUp/tearDown pair."""
    mesh = initialize_distributed(tensor_model_parallel_size,
                                  pipeline_model_parallel_size, **kw)
    cache = collections.OrderedDict()
    _CACHE_MAX = 32

    def run(f, *args, in_specs=P(), out_specs=P(), check_vma=True):
        # Cache the jitted wrapper per (f identity, specs) so repeated
        # calls with a STABLE function skip retrace/recompile. Pass a
        # module-level or otherwise long-lived fn for this to help: a
        # fresh lambda each call is a new identity and always misses.
        # LRU-bounded so closure-per-call misses cannot pin unbounded
        # executables/captured arrays until teardown.
        key = (f, str(in_specs), str(out_specs), check_vma)
        if key in cache:
            cache.move_to_end(key)
        else:
            if len(cache) >= _CACHE_MAX:
                cache.popitem(last=False)
            cache[key] = jax.jit(jax.shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=check_vma))
        return cache[key](*args)

    try:
        yield run
    finally:
        parallel_state.destroy_model_parallel()


def print_separator(message: str, width: int = 70):
    """Reference test-output separator."""
    filler = "-" * max(width - len(message) - 2, 0)
    print(f"\n{'-' * width}\n {message} {filler}\n{'-' * width}", flush=True)
