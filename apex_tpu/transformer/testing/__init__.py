"""apex_tpu.transformer.testing — shared parallelism test helpers
(reference: ``apex/transformer/testing/`` (U))."""

from apex_tpu.transformer.testing.commons import (  # noqa: F401
    IdentityLayer,
    ToyParallelMLP,
    initialize_distributed,
    model_parallel_harness,
    print_separator,
    set_random_seed,
)
