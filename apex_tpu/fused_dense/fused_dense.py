"""Fused dense layers (reference: ``apex/fused_dense/fused_dense.py`` +
``csrc/fused_dense.cpp``/``fused_dense_cuda.cu``, SURVEY.md §2.1/§2.2).

The reference wraps cublasLt GEMM epilogues (bias, bias+gelu) so the
bias/activation rides inside the GEMM kernel. XLA performs the same
epilogue fusion on the jitted graph, so these modules provide the
reference's API shape — ``FusedDense``, ``DenseNoBias``,
``FusedDenseGeluDense`` — over plain ``jnp`` matmuls with fp32
accumulation on the MXU.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class FusedDense(nn.Module):
    """Linear + bias in one fused pass (reference ``FusedDense``)."""

    in_features: int
    out_features: int
    bias: bool = True
    params_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (self.in_features, self.out_features), self.params_dtype)
        y = jnp.matmul(x, kernel.astype(x.dtype),
                       preferred_element_type=jnp.float32)
        if self.bias:
            b = self.param("bias", nn.initializers.zeros,
                           (self.out_features,), self.params_dtype)
            y = y + b.astype(jnp.float32)
        return y.astype(x.dtype)


class DenseNoBias(nn.Module):
    """Reference ``DenseNoBias``: GEMM only."""

    in_features: int
    out_features: int
    params_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (self.in_features, self.out_features), self.params_dtype)
        return jnp.matmul(x, kernel.astype(x.dtype),
                          preferred_element_type=jnp.float32).astype(x.dtype)


class FusedDenseGeluDense(nn.Module):
    """Linear+bias → GELU → Linear+bias (reference
    ``FusedDenseGeluDense``, the transformer-MLP shape the cublasLt
    epilogue chain targets)."""

    in_features: int
    intermediate_features: int
    out_features: int
    params_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = FusedDense(self.in_features, self.intermediate_features,
                       params_dtype=self.params_dtype, name="dense1")(x)
        h = jax.nn.gelu(h)
        return FusedDense(self.intermediate_features, self.out_features,
                          params_dtype=self.params_dtype, name="dense2")(h)
