"""apex.fused_dense parity surface (reference: ``apex/fused_dense``)."""

from apex_tpu.fused_dense.fused_dense import (
    DenseNoBias,
    FusedDense,
    FusedDenseGeluDense,
)

__all__ = ["DenseNoBias", "FusedDense", "FusedDenseGeluDense"]
