"""Batch loaders over tokenized corpora with a C hot path and
background prefetch.

Design (TPU-first): the device step is the bottleneck resource, so the
loader's job is to make batch assembly invisible — a daemon thread
builds the next ``prefetch`` batches into fresh numpy buffers while the
accelerator runs, and the iterator hands them over without copies. All
randomness is derived from ``(seed, epoch)`` / ``(seed, batch_index)``
pairs, so a run is reproducible regardless of prefetch timing.
"""

from __future__ import annotations

import ctypes
import os
import queue
import threading
from typing import Optional, Sequence

import numpy as np

from apex_tpu._native import build_ctypes_lib

_LIB = None
_TRIED = False
_SRC = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir,
                    "csrc", "dataloader.c")


def _build_and_load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    lib = build_ctypes_lib(_SRC, "dataloader")
    if lib is not None:
        lib.apex_shuffle_indices.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_uint64]
        lib.apex_gather_rows.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p,
            ctypes.c_size_t, ctypes.c_void_p]
        lib.apex_mlm_mask.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_size_t, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_uint32,
            ctypes.c_uint64]
    _LIB = lib
    return _LIB


def native_available() -> bool:
    return _build_and_load() is not None


def _shuffled_indices(n: int, seed: int) -> np.ndarray:
    lib = _build_and_load()
    idx = np.empty(n, np.uint64)
    if lib is not None:
        lib.apex_shuffle_indices(idx.ctypes.data_as(ctypes.c_void_p), n,
                                 ctypes.c_uint64(seed))
        return idx
    rng = np.random.RandomState(seed & 0x7FFFFFFF)
    return rng.permutation(n).astype(np.uint64)


def _gather_rows(corpus: np.ndarray, idx: np.ndarray) -> np.ndarray:
    lib = _build_and_load()
    out = np.empty((len(idx), corpus.shape[1]), np.int32)
    if lib is not None:
        lib.apex_gather_rows(
            corpus.ctypes.data_as(ctypes.c_void_p), corpus.shape[1],
            np.ascontiguousarray(idx).ctypes.data_as(ctypes.c_void_p),
            len(idx), out.ctypes.data_as(ctypes.c_void_p))
        return out
    np.take(corpus, idx.astype(np.int64), axis=0, out=out)
    return out


def _mlm_mask(tokens: np.ndarray, vocab_size: int, mask_id: int,
              special_ids: np.ndarray, prob: float, seed: int):
    lib = _build_and_load()
    ids = np.empty_like(tokens)
    labels = np.empty_like(tokens)
    q16 = min(65535, max(0, int(prob * 65536)))
    if lib is not None:
        lib.apex_mlm_mask(
            tokens.ctypes.data_as(ctypes.c_void_p),
            ids.ctypes.data_as(ctypes.c_void_p),
            labels.ctypes.data_as(ctypes.c_void_p),
            tokens.size, vocab_size, mask_id,
            special_ids.ctypes.data_as(ctypes.c_void_p), special_ids.size,
            q16, ctypes.c_uint64(seed))
        return ids, labels
    # numpy fallback: same contract, different RNG stream
    rng = np.random.RandomState(seed & 0x7FFFFFFF)
    flat = tokens.reshape(-1)
    ids_f = flat.copy()
    labels_f = np.full_like(flat, -1)
    eligible = ~np.isin(flat, special_ids)
    sel = eligible & (rng.rand(flat.size) < prob)
    labels_f[sel] = flat[sel]
    kind = rng.rand(flat.size)
    mask_pos = sel & (kind < 0.8)
    rand_pos = sel & (kind >= 0.8) & (kind < 0.9)
    ids_f[mask_pos] = mask_id
    ids_f[rand_pos] = rng.randint(0, vocab_size, rand_pos.sum())
    return ids_f.reshape(tokens.shape), labels_f.reshape(tokens.shape)


class _PrefetchIterator:
    """Daemon-thread prefetcher: builds up to ``depth`` batches ahead.

    Worker exceptions are enqueued and re-raised in the consumer (a
    batch-assembly error crashes the training loop, never hangs it), and
    abandoning the iterator early (``break``) releases the worker via
    :meth:`close` — the bounded ``put`` polls a stop event instead of
    blocking forever."""

    _DONE = object()

    def __init__(self, make_batch, n_batches: int, depth: int):
        self._q = queue.Queue(maxsize=max(depth, 1))
        self._stop = threading.Event()

        def put(item) -> bool:
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def work():
            try:
                for i in range(n_batches):
                    if not put(make_batch(i)):
                        return
            except BaseException as e:  # noqa: BLE001 — re-raised consumer-side
                put(e)
                return
            put(self._DONE)

        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()

    def close(self):
        """Release the worker thread (called on early abandonment)."""
        self._stop.set()
        while True:  # drain so a blocked put wakes promptly
            try:
                self._q.get_nowait()
            except queue.Empty:
                break

    def __del__(self):
        self.close()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._DONE:
            raise StopIteration
        if isinstance(item, BaseException):
            raise item
        return item


class _BaseLoader:
    """Shared epoch/shuffle/prefetch machinery.

    corpus: (N, S) int32 array of tokenized sequences (memmap works).

    ``drop_last=False`` (torch-DataLoader parity) keeps the epoch tail
    when the corpus is not batch-divisible — but with STATIC shapes:
    the final batch is padded to ``batch_size`` by repeating its last
    valid row, and every yielded batch gains a trailing
    ``sample_weights`` float32 (batch,) array (1.0 valid / 0.0 pad) so
    losses mask the padding without any per-tail recompile. (A
    torch-style smaller tail batch would change the jit input shape and
    force an XLA recompile each epoch.)
    """

    def __init__(self, corpus, batch_size: int, *, seed: int = 0,
                 shuffle: bool = True, drop_last: bool = True,
                 prefetch: int = 2):
        self.corpus = np.ascontiguousarray(np.asarray(corpus, np.int32))
        if self.corpus.ndim != 2:
            raise ValueError(
                f"corpus must be (num_sequences, seq_len), got "
                f"{self.corpus.shape}")
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.prefetch = int(prefetch)
        self.epoch = 0

    def __len__(self):
        n, b = len(self.corpus), self.batch_size
        return n // b if self.drop_last else -(-n // b)

    def valid_rows(self, b: int) -> int:
        """Number of non-padding rows in batch ``b`` (== batch_size for
        all but a ``drop_last=False`` epoch tail)."""
        if b < 0 or b >= len(self):
            raise IndexError(f"batch {b} out of range [0, {len(self)})")
        if b < len(self.corpus) // self.batch_size:
            return self.batch_size
        return len(self.corpus) - b * self.batch_size

    def _batch_rows(self, order: np.ndarray, b: int):
        """(row indices padded to batch_size, sample weights)."""
        rows = order[b * self.batch_size:(b + 1) * self.batch_size]
        valid = len(rows)
        if valid < self.batch_size:  # pad-and-mask the epoch tail
            rows = np.concatenate(
                [rows, np.repeat(rows[-1:], self.batch_size - valid)])
        weights = np.zeros(self.batch_size, np.float32)
        weights[:valid] = 1.0
        return rows, weights

    def set_epoch(self, epoch: int):
        """Reshuffle for a new epoch (distributed-sampler analog)."""
        self.epoch = int(epoch)

    def _epoch_indices(self) -> np.ndarray:
        if not self.shuffle:
            return np.arange(len(self.corpus), dtype=np.uint64)
        return _shuffled_indices(len(self.corpus),
                                 (self.seed << 20) ^ self.epoch)

    def _make_batch(self, order: np.ndarray, b: int):
        raise NotImplementedError

    def __iter__(self):
        order = self._epoch_indices()
        return _PrefetchIterator(
            lambda b: self._make_batch(order, b), len(self), self.prefetch)


class MLMBatchLoader(_BaseLoader):
    """BERT masked-LM batches: yields ``(input_ids, mlm_labels)`` int32
    numpy arrays of shape (batch, seq); labels are -1 on unmasked
    positions (the convention ``models.bert.pretraining_loss`` expects).

    With ``drop_last=False`` every batch is
    ``(input_ids, mlm_labels, sample_weights)``; padding rows of the
    epoch tail carry all ``-1`` labels (zero MLM loss) and weight 0.
    """

    def __init__(self, corpus, batch_size: int, vocab_size: int,
                 mask_id: int, special_ids: Sequence[int] = (),
                 mask_prob: float = 0.15, **kw):
        super().__init__(corpus, batch_size, **kw)
        self.vocab_size = int(vocab_size)
        self.mask_id = int(mask_id)
        self.special_ids = np.asarray(sorted(set(special_ids)), np.int32)
        self.mask_prob = float(mask_prob)

    def _make_batch(self, order: np.ndarray, b: int):
        rows, weights = self._batch_rows(order, b)
        tokens = _gather_rows(self.corpus, rows)
        ids, labels = _mlm_mask(
            tokens, self.vocab_size, self.mask_id, self.special_ids,
            self.mask_prob,
            (self.seed << 40) ^ (self.epoch << 20) ^ (b + 1))
        if self.drop_last:
            return ids, labels
        labels[weights == 0.0] = -1  # padding rows: no loss positions
        return ids, labels, weights


class CausalLMBatchLoader(_BaseLoader):
    """GPT-style batches: yields ``input_ids`` (batch, seq) int32; the
    next-token shift lives in ``models.gpt.lm_loss``. With
    ``drop_last=False`` every batch is ``(input_ids, sample_weights)``
    (see :class:`_BaseLoader`)."""

    def _make_batch(self, order: np.ndarray, b: int):
        rows, weights = self._batch_rows(order, b)
        ids = _gather_rows(self.corpus, rows)
        if self.drop_last:
            return ids
        return ids, weights
