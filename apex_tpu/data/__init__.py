"""apex_tpu.data — native-backed input pipeline.

The reference leaves data loading to torch ``DataLoader``/DALI (C++
under the hood); this package is the TPU rebuild's equivalent tier: the
hot path (epoch shuffle, batch row gather, BERT MLM masking) runs in C
(``csrc/dataloader.c`` via ctypes, same build scheme as
:mod:`apex_tpu._native`), and a background-thread prefetcher overlaps
host batch assembly with device steps. Numpy fallbacks keep the package
working without a compiler.
"""

from apex_tpu.data.loader import (  # noqa: F401
    CausalLMBatchLoader,
    MLMBatchLoader,
    native_available,
)
