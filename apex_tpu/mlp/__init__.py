"""apex.mlp parity surface (reference: ``apex/mlp/__init__.py``)."""

from apex_tpu.mlp.mlp import MLP

__all__ = ["MLP"]
