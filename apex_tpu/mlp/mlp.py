"""Fused MLP (reference: ``apex/mlp/mlp.py`` + ``csrc/mlp.cpp``/
``mlp_cuda.cu``, SURVEY.md §2.1/§2.2).

The reference exists because eager torch launches one GEMM + one bias +
one activation kernel per layer; its CUDA ext runs the whole chain in one
call. Under XLA the jitted chain IS the fused program (GEMM + bias +
activation epilogues fuse into the matmul), so the module's job here is
pure API parity: the ``mlp_sizes`` constructor shape, ``bias``/
``activation`` knobs, and flat ``weights``/``biases`` attribute access.

Matmuls carry ``preferred_element_type=fp32`` so bf16 activations hit the
MXU with fp32 accumulation.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.fused_dense import FusedDense

_ACTIVATIONS = {
    "none": lambda x: x,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "gelu": jax.nn.gelu,  # extension over the reference's {none,relu,sigmoid}
}


class MLP(nn.Module):
    """Chain of Linear(+bias)(+activation) layers.

    Reference constructor: ``MLP(mlp_sizes, bias=True, relu=True,
    activation='relu')`` — ``mlp_sizes[0]`` is the input width, each
    subsequent entry a layer output width. The activation is applied
    after every layer except the last (reference ``mlp.cpp`` semantics).

    Layers are :class:`~apex_tpu.fused_dense.FusedDense`, so bf16
    activations run single-pass MXU matmuls with fp32 accumulation.
    """

    mlp_sizes: Sequence[int]
    bias: bool = True
    activation: str = "relu"
    params_dtype: jnp.dtype = jnp.float32

    def setup(self):
        if len(self.mlp_sizes) < 2:
            raise ValueError("mlp_sizes needs an input size and >=1 layer")
        if self.activation not in _ACTIVATIONS:
            raise ValueError(
                f"activation must be one of {sorted(_ACTIVATIONS)}, "
                f"got {self.activation!r}")
        self.layers = [
            FusedDense(
                self.mlp_sizes[i],
                out,
                bias=self.bias,
                params_dtype=self.params_dtype,
                name=f"layer_{i}",
            )
            for i, out in enumerate(self.mlp_sizes[1:])
        ]

    def __call__(self, x):
        act = _ACTIVATIONS[self.activation]
        n = len(self.layers)
        for i, layer in enumerate(self.layers):
            y = layer(x)
            x = act(y) if i < n - 1 else y
        return x
