from apex_tpu.utils.faults import (  # noqa: F401
    TRANSIENT_ERRORS,
    DispatchFailedError,
    FaultPlan,
    FaultSpec,
    SimulatedCrash,
    TransientDispatchError,
    nan_corrupt,
)
from apex_tpu.utils.pytree import (  # noqa: F401
    all_finite,
    flatten_buckets,
    global_norm,
    ravel_list,
    tree_cast,
    tree_select,
    unravel_list,
)
