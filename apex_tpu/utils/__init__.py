from apex_tpu.utils.pytree import (  # noqa: F401
    all_finite,
    flatten_buckets,
    global_norm,
    ravel_list,
    tree_cast,
    tree_select,
    unravel_list,
)
