"""Collective helpers shared across the parallel/transformer layers."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def compat_shard_map(f, mesh, in_specs, out_specs):
    """``shard_map`` across the JAX vintages this repo runs on, with the
    replication check OFF on every vintage.

    Newer JAX exposes ``jax.shard_map`` (vma-checked via ``check_vma``);
    the 0.4.x line only has ``jax.experimental.shard_map.shard_map``
    (``check_rep``), whose pass cannot infer replication through a
    ``lax.scan`` carry (it aborts with "Scan carry input and output got
    mismatched replication types"). The check is disabled on BOTH APIs
    — not just the broken one — because the vma-marking discipline the
    two vintages expect differs, and a program that must trace on both
    cannot satisfy either checker portably. Callers therefore OWN their
    replication discipline: every in-repo user replicates state in,
    explicitly psums/pmeans/all_gathers anything device-varying before
    an ``out_specs=P()`` output, and certifies the result in tests
    (tests/test_train_step.py drives the composed step on the 8-device
    mesh). Do not route an out_specs=P() output through this wrapper
    without one of those collectives."""
    try:
        sm = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm
    for kw in ("check_vma", "check_rep"):
        try:
            return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **{kw: False})
        except TypeError:
            continue
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def mark_varying(x, axis_names):
    """Idempotent ``pcast(..., to='varying')`` over a pytree: only axes not
    already in a leaf's varying set are cast (raw pcast raises on
    already-varying input)."""
    if isinstance(axis_names, str):
        axis_names = (axis_names,)

    def one(a):
        try:
            vma = jax.typeof(a).vma
        except (AttributeError, TypeError):
            vma = frozenset()
        missing = tuple(ax for ax in axis_names if ax not in vma)
        if not missing:
            return a
        return jax.lax.pcast(a, missing, to="varying")

    return jax.tree.map(one, x)


def axis_is_bound(axis_name: str) -> bool:
    """Whether ``axis_name`` is currently a bound collective axis
    (inside shard_map/pmap over it). Always returns a bool: if the
    axis-env introspection API moves (it is private), falls back to
    probing ``axis_index``, which raises NameError on unbound names."""
    try:
        from jax._src import core as _core

        return bool(_core.get_axis_env().axis_exists(axis_name))
    except Exception:
        pass
    try:
        jax.lax.axis_index(axis_name)
        return True
    except Exception:
        return False


def psum_groups(x, axis_name: str, groups: Optional[Sequence[Sequence[int]]] = None):
    """``lax.psum`` with subgroup support that works under ``shard_map``.

    ``axis_index_groups`` is the reference ``process_group`` analog
    (SyncBatchNorm subgroups, DDP partial worlds). This JAX version's
    shard_map lowering raises NotImplementedError for grouped psum of
    traced arrays, so when groups are given we fall back to an explicit
    all_gather + static 0/1 group-mask contraction — semantically
    identical, and XLA folds the mask multiply into the reduction.
    """
    if groups is None:
        return jax.lax.psum(x, axis_name)
    try:
        return jax.lax.psum(x, axis_name, axis_index_groups=groups)
    except NotImplementedError:
        pass
    world = jax.lax.psum(1, axis_name, axis_index_groups=None)
    membership = np.zeros((world, world), np.float32)
    for group in groups:
        for i in group:
            for j in group:
                membership[i, j] = 1.0
    gathered = jax.lax.all_gather(x, axis_name)  # (world, ...)
    mask = jnp.asarray(membership)[jax.lax.axis_index(axis_name)]
    return jnp.tensordot(mask, gathered.astype(jnp.float32), axes=1).astype(x.dtype)


def group_size(groups: Optional[Sequence[Sequence[int]]], axis_name: str):
    """Size of the caller's reduction group (static when groups are)."""
    if groups is None:
        return jax.lax.psum(1, axis_name)
    sizes = {len(g) for g in groups}
    if len(sizes) == 1:
        return sizes.pop()
    world = jax.lax.psum(1, axis_name)
    per_dev = np.zeros((world,), np.float32)
    for g in groups:
        for i in g:
            per_dev[i] = len(g)
    return jnp.asarray(per_dev)[jax.lax.axis_index(axis_name)]
