"""End-to-end data integrity for host-side artifacts
(docs/robustness.md, "Data integrity").

Every host artifact the serving stack moves between processes,
replicas, or memory tiers — snapshot/checkpoint records, spilled KV
blocks, migration records, cross-replica KV payloads, every RPC frame
on the process-replica wire (``serving/wire.py``) — is consumed by
machinery that TRUSTS its bytes. A bit flip in host RAM, a truncated
copy, or a buggy transport therefore does not crash: it silently
serves wrong tokens, re-prefills a corrupted history, or attends
against another request's KV. This module makes that trust explicit
and checkable:

- :func:`payload_checksum` — SHA-256 over the canonical bytes of a
  numpy-array payload dict (key names, dtypes, shapes, raw bytes, in
  sorted key order). The checksum of a spilled/transported KV block.
- :func:`record_checksum` — SHA-256 over the canonical JSON encoding
  (sorted keys, no whitespace) of a JSON-able record, EXCLUDING the
  ``"checksum"`` field itself. Stable across a ``json.dumps`` →
  ``json.loads`` round trip (the snapshot wire format), so a record
  sealed in one process verifies in another.
- :func:`seal_record` / :func:`verify_record` — attach / check the
  embedded checksum. A record WITHOUT a checksum verifies trivially:
  checksum-less legacy artifacts stay loadable (the PR 9 torn-marker
  lesson — new metadata must never orphan old artifacts), and the
  detection guarantee is stated honestly as covering sealed artifacts
  only.
- :class:`IntegrityError` — the typed verification failure, carrying
  the consumption site. NEVER caught-and-ignored: every consumer
  routes it through an existing degradation path (a corrupt spill
  entry is a cache miss, a corrupt checkpoint falls back to fresh
  re-injection, a corrupt migration import is refused so the source
  keeps the request) and counts the detection.

Checksums are detection, not correction: the recovery story is the
redundancy the engine already has — recompute for cache tiers, the
router's own request copies for failover, the source replica for
refused migrations. See docs/robustness.md for the threat model and
the per-artifact routing table.
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, Mapping, Optional

import numpy as np

# the embedded-checksum field name shared by every sealed record
CHECKSUM_KEY = "checksum"


class IntegrityError(RuntimeError):
    """A checksummed artifact failed verification at consumption.

    Carries the consumption ``site`` (``"spill_get"``, ``"restore"``,
    ``"import"``, ``"checkpoint"``, ``"wire"``, ...) so counters and
    the flight recorder can attribute the detection. Raised only where
    refusal is the correct degradation (migration imports, operator
    restores, torn RPC frames — the parent resends, the worker asks
    for a resend); cache-tier consumers detect-and-discard instead of
    raising."""

    def __init__(self, site: str, detail: str):
        super().__init__(f"integrity check failed at {site!r}: {detail}")
        self.site = site
        self.detail = detail


def payload_checksum(payload: Mapping[str, object]) -> str:
    """SHA-256 over a payload dict's canonical bytes.

    Only numpy-array values participate (string/None metadata keys —
    e.g. an embedded ``"checksum"`` riding a transported payload — are
    skipped), each contributing its key name, dtype, shape, and raw
    C-order bytes, in sorted key order: two payloads checksum equal
    iff their array contents are equal."""
    h = hashlib.sha256()
    for key in sorted(payload):
        a = payload[key]
        if not isinstance(a, np.ndarray):
            continue
        a = np.ascontiguousarray(a)
        h.update(key.encode("utf-8"))
        h.update(str(a.dtype).encode("ascii"))
        h.update(repr(a.shape).encode("ascii"))
        h.update(a.tobytes())
    return h.hexdigest()


def _canonical_json(record: Mapping) -> bytes:
    body = {k: v for k, v in record.items() if k != CHECKSUM_KEY}
    # normalize through one JSON round trip FIRST: the wire format
    # stringifies non-string dict keys (and turns tuples into lists),
    # which changes sort_keys ordering — a record must checksum
    # identically before and after riding a file/socket, or every
    # sealed artifact would read as corrupt on arrival
    body = json.loads(json.dumps(body))
    return json.dumps(body, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def record_checksum(record: Mapping) -> str:
    """SHA-256 over a JSON-able record's canonical encoding (sorted
    keys, compact separators), excluding the embedded checksum field.
    ``json`` round-trips finite floats exactly (``repr`` encoding), so
    the checksum survives the snapshot's serialize → file → parse
    path bit-for-bit."""
    return hashlib.sha256(_canonical_json(record)).hexdigest()


def seal_record(record: Dict) -> Dict:
    """Embed the record's checksum under :data:`CHECKSUM_KEY` (in
    place; also returned). Seal LAST — any mutation after sealing is
    indistinguishable from corruption, which is the point."""
    record[CHECKSUM_KEY] = record_checksum(record)
    return record


def verify_record(record: Mapping, site: str) -> bool:
    """Check a record against its embedded checksum.

    Returns True when the record verifies, False when it carries no
    checksum (legacy artifact — acceptable by policy, distinguishable
    by the caller via :func:`is_sealed`). Raises
    :class:`IntegrityError` on a mismatch."""
    expect = record.get(CHECKSUM_KEY)
    if expect is None:
        return False
    actual = record_checksum(record)
    if actual != expect:
        raise IntegrityError(
            site, f"record checksum {actual[:16]}... != sealed "
                  f"{str(expect)[:16]}...")
    return True


def is_sealed(record: Mapping) -> bool:
    return record.get(CHECKSUM_KEY) is not None


def verify_payload(payload: Mapping[str, object],
                   expect: Optional[str], site: str) -> bool:
    """Check a payload dict against a detached checksum (None =
    legacy/unchecksummed, verifies trivially as False). Raises
    :class:`IntegrityError` on a mismatch."""
    if expect is None:
        return False
    actual = payload_checksum(payload)
    if actual != expect:
        raise IntegrityError(
            site, f"payload checksum {actual[:16]}... != recorded "
                  f"{str(expect)[:16]}...")
    return True
