"""Pytree / flat-buffer utilities.

TPU-native replacement for the reference's ``apex_C`` extension
(``csrc/flatten_unflatten.cpp``, SURVEY.md §2.2): flattening a list of
tensors into one contiguous buffer and back. Used for *communication*
buffers (DDP bucket allreduce), where one contiguous collective is the
point. Do NOT use it as a compute-fusion device: huge raveled 1-D buffers
interact badly with the TPU tiled layout (see the horizontal-packing
pathology documented in :mod:`apex_tpu.ops.multi_tensor`, which does
per-leaf math for exactly that reason).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def ravel_list(leaves):
    """Flatten a list of arrays into one contiguous 1-D buffer.

    Analog of ``apex_C.flatten``. Returns the flat buffer plus the
    (shape, dtype, size) metadata needed by :func:`unravel_list`.
    """
    leaves = list(leaves)
    if not leaves:
        return jnp.zeros((0,), jnp.float32), []
    meta = [(x.shape, x.dtype, x.size) for x in leaves]
    flat = jnp.concatenate([jnp.ravel(x) for x in leaves])
    return flat, meta


def unravel_list(flat, meta):
    """Inverse of :func:`ravel_list` (analog of ``apex_C.unflatten``)."""
    out = []
    offset = 0
    for shape, dtype, size in meta:
        out.append(jax.lax.dynamic_slice_in_dim(flat, offset, size).reshape(shape).astype(dtype))
        offset += size
    return out


def flatten_buckets(leaves, bucket_numel):
    """Partition a list of arrays into buckets of at most ``bucket_numel``
    total elements (greedy, preserving order), mirroring the reference DDP's
    ``message_size``-element buckets (``apex/parallel/distributed.py``).

    Returns a list of (indices, flat_buffer, meta) triples.
    """
    buckets = []
    cur_idx, cur, cur_numel = [], [], 0
    for i, leaf in enumerate(leaves):
        if cur and cur_numel + leaf.size > bucket_numel:
            flat, meta = ravel_list(cur)
            buckets.append((cur_idx, flat, meta))
            cur_idx, cur, cur_numel = [], [], 0
        cur_idx.append(i)
        cur.append(leaf)
        cur_numel += leaf.size
    if cur:
        flat, meta = ravel_list(cur)
        buckets.append((cur_idx, flat, meta))
    return buckets


def all_finite(tree):
    """True iff every element of every floating leaf is finite.

    The TPU-native overflow check: apex reads back a ``noop_flag`` buffer
    written by ``multi_tensor_scale`` (a host sync); here the flag stays a
    jit-carried bool consumed by ``lax.cond`` / ``jnp.where`` step-skipping.
    """
    leaves = [x for x in jax.tree.leaves(tree) if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)]
    if not leaves:
        return jnp.asarray(True)
    finite = [jnp.all(jnp.isfinite(x)) for x in leaves]
    return jnp.stack(finite).all()


def tree_select(pred, on_true, on_false):
    """Elementwise pytree select: ``pred ? on_true : on_false``.

    Used for overflow step-skipping: both the applied and skipped optimizer
    states are computed in-graph and selected, avoiding retrace-prone Python
    control flow (SURVEY.md §7 hard part 1).
    """
    return jax.tree.map(lambda a, b: jnp.where(pred, a, b), on_true, on_false)


def tree_cast(tree, dtype):
    """Cast every floating-point leaf of ``tree`` to ``dtype``."""
    def cast(x):
        x = jnp.asarray(x)
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x
    return jax.tree.map(cast, tree)


def global_norm(tree, ord=2):  # noqa: A002
    """Global L2 norm over all leaves (fp32 accumulation)."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    if ord != 2:
        raise NotImplementedError("only the L2 global norm is supported")
    sq = [jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves]
    return jnp.sqrt(jnp.stack(sq).sum())
