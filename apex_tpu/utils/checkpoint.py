"""Checkpoint round-trip for (params, optimizer state, scaler state).

SURVEY.md §5 checkpoint/resume row: the reference's contractual surface
is small — ``amp.state_dict()`` round-trips loss-scaler state, and
optimizers expose ``state_dict`` with step counts — but a real training
harness needs the full (params, opt_state, scaler_state) triple on disk.
The TPU-native answer is orbax over a single flat pytree, which
preserves shardings and restores on any topology.

Usage::

    save_checkpoint(dir, step, params=params, opt_state=state,
                    scaler_state=scaler_state)
    restored = load_checkpoint(dir, step=None,  # None = latest
                               template=dict(params=params,
                                             opt_state=state,
                                             scaler_state=scaler_state))

The template supplies structure (NamedTuples, dtypes) for restore; pass
abstract ``jax.eval_shape`` results to avoid materializing a throwaway
tree. ``amp.state_dict()`` remains the scaler-only reference-shaped
surface; this helper is the full-training-state tier above it.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def checkpoint_path(directory: str, step: int) -> str:
    # orbax requires absolute paths ("Checkpoint path should be absolute")
    return os.path.join(os.path.abspath(os.fspath(directory)),
                        f"step_{step:09d}")


def save_checkpoint(directory: str, step: int, **trees) -> str:
    """Save named pytrees (params=..., opt_state=..., scaler_state=...)
    as one checkpoint under ``directory/step_NNNNNNNNN``. Returns the
    path. Overwrites an existing checkpoint at the same step (resume
    after preemption re-saves the same step)."""
    path = checkpoint_path(directory, step)
    payload = {k: v for k, v in trees.items() if v is not None}
    payload["_step"] = step
    _checkpointer().save(path, payload, force=True)
    return path


def latest_step(directory: str) -> Optional[int]:
    """Highest step with a checkpoint in ``directory``, or None."""
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        if name.startswith("step_"):
            try:
                steps.append(int(name[len("step_"):]))
            except ValueError:
                continue
    return max(steps) if steps else None


def load_checkpoint(directory: str, step: Optional[int] = None,
                    template: Optional[Any] = None):
    """Restore a checkpoint (``step=None`` → latest).

    ``template`` is a pytree of arrays or ShapeDtypeStructs with the
    SAME named-tree structure passed to :func:`save_checkpoint`; it
    restores container types (NamedTuples) that serialization flattens.
    Returns the restored dict of trees (plus ``_step``).
    """
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory!r}")
    path = checkpoint_path(directory, step)
    if template is not None:
        item = dict(template)
        item["_step"] = step
        return _checkpointer().restore(path, item=item)
    return _checkpointer().restore(path)
