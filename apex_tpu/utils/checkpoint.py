"""Checkpoint round-trip for (params, optimizer state, scaler state).

SURVEY.md §5 checkpoint/resume row: the reference's contractual surface
is small — ``amp.state_dict()`` round-trips loss-scaler state, and
optimizers expose ``state_dict`` with step counts — but a real training
harness needs the full (params, opt_state, scaler_state) triple on disk.
The TPU-native answer is orbax over a single flat pytree, which
preserves shardings and restores on any topology.

Usage::

    save_checkpoint(dir, step, params=params, opt_state=state,
                    scaler_state=scaler_state)
    restored = load_checkpoint(dir, step=None,  # None = latest
                               template=dict(params=params,
                                             opt_state=state,
                                             scaler_state=scaler_state))

The template supplies structure (NamedTuples, dtypes) for restore; pass
abstract ``jax.eval_shape`` results to avoid materializing a throwaway
tree. ``amp.state_dict()`` remains the scaler-only reference-shaped
surface; this helper is the full-training-state tier above it.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def checkpoint_path(directory: str, step: int) -> str:
    # orbax requires absolute paths ("Checkpoint path should be absolute")
    return os.path.join(os.path.abspath(os.fspath(directory)),
                        f"step_{step:09d}")


def _marker_path(directory: str, step: int) -> str:
    """The step's terminal commit marker — a sibling manifest file, NOT
    inside the orbax directory (orbax owns that layout). Its existence
    is the definition of "this checkpoint finished saving"."""
    return checkpoint_path(directory, step) + ".complete"


def _write_marker(directory: str, step: int, names,
                  fingerprint: Optional[dict] = None) -> None:
    """The terminal write of a save: a small JSON manifest (step + tree
    names + optional topology fingerprint), written to a temp file and
    atomically renamed into place so the marker itself can never be
    observed torn."""
    marker = _marker_path(directory, step)
    tmp = marker + ".tmp"
    manifest = {"step": int(step), "trees": sorted(names)}
    if fingerprint:
        manifest["fingerprint"] = fingerprint
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, marker)


def read_marker(directory: str, step: int) -> Optional[dict]:
    """The step's commit-marker manifest as a dict, or None when the
    marker does not exist (torn save, or a legacy pre-marker
    directory). Legacy markers lack the ``"fingerprint"`` key."""
    marker = _marker_path(directory, step)
    if not os.path.exists(marker):
        return None
    with open(marker) as f:
        return json.load(f)


def state_mesh_shape(state) -> Optional[list]:
    """The mesh fingerprint of a pytree: ``[[axis, size], ...]`` from
    the first leaf whose sharding is a mesh-backed ``NamedSharding``,
    or None for a meshless (single-device / host) tree. JSON-shaped
    (lists, not tuples) so it round-trips through the marker manifest
    unchanged — equality against a freshly computed fingerprint is the
    resume-compatibility check."""
    for leaf in jax.tree.leaves(state):
        sharding = getattr(leaf, "sharding", None)
        mesh = getattr(sharding, "mesh", None)
        shape = getattr(mesh, "shape", None)
        if shape:
            return [[str(axis), int(size)] for axis, size in shape.items()]
    return None


def save_checkpoint(directory: str, step: int,
                    fingerprint: Optional[dict] = None, **trees) -> str:
    """Save named pytrees (params=..., opt_state=..., scaler_state=...)
    as one checkpoint under ``directory/step_NNNNNNNNN``. Returns the
    path. Overwrites an existing checkpoint at the same step (resume
    after preemption re-saves the same step). ``fingerprint`` (a small
    JSON-able dict, e.g. ``{"mesh_shape": state_mesh_shape(state)}``)
    rides in the commit marker for load-time topology checks.

    **Crash-safe**: the payload write is finalized by an atomic
    manifest/marker write (``step_NNNNNNNNN.complete``), and
    :func:`latest_step` / :func:`load_checkpoint` only see steps whose
    marker exists — a process killed mid-save leaves a torn payload
    that resume simply skips (it picks the previous complete step)
    instead of loading garbage. Overwriting an existing step removes
    its marker FIRST, so a crash mid-overwrite also reads as
    incomplete rather than serving the half-replaced payload."""
    path = checkpoint_path(directory, step)
    marker = _marker_path(directory, step)
    # flip the directory to marker-governed BEFORE the payload write:
    # a fresh directory whose very first save is killed mid-payload
    # must read as torn, not fall into the legacy (pre-marker) path
    os.makedirs(os.path.abspath(os.fspath(directory)), exist_ok=True)
    era = os.path.join(os.path.abspath(os.fspath(directory)),
                       _ERA_SENTINEL)
    if not os.path.exists(era):
        with open(era, "w") as f:
            f.write("markers govern this directory\n")
    if os.path.exists(marker):
        os.remove(marker)
    payload = {k: v for k, v in trees.items() if v is not None}
    payload["_step"] = step
    _checkpointer().save(path, payload, force=True)
    _write_marker(directory, step, payload.keys(), fingerprint=fingerprint)
    return path


_ERA_SENTINEL = ".checkpoint-markers"


def _directory_is_marker_governed(directory: str) -> bool:
    """True once the directory has ever been written by marker-era
    code: the era sentinel (written BEFORE the first payload, so even
    a torn very-first save is governed) or any step marker."""
    if os.path.exists(os.path.join(directory, _ERA_SENTINEL)):
        return True
    return any(name.endswith(".complete")
               for name in os.listdir(directory))


def latest_step(directory: str) -> Optional[int]:
    """Highest COMPLETE step in ``directory`` (its commit marker
    exists), or None. Unfinished saves — payload present, marker
    absent — are invisible here by design.

    **Legacy fallback**: a directory containing NO markers at all was
    written entirely by the pre-marker code; its steps are all treated
    as complete (exactly the old behavior), so upgrading never makes
    an existing run's checkpoints invisible. The moment one marker
    exists, the directory is marker-governed and marker-less steps
    read as torn."""
    if not os.path.isdir(directory):
        return None
    strict = _directory_is_marker_governed(directory)
    steps = []
    for name in os.listdir(directory):
        if name.startswith("step_") and not name.endswith((".complete",
                                                           ".tmp")):
            try:
                step = int(name[len("step_"):])
            except ValueError:
                continue
            if not strict or os.path.exists(_marker_path(directory, step)):
                steps.append(step)
    return max(steps) if steps else None


def load_checkpoint(directory: str, step: Optional[int] = None,
                    template: Optional[Any] = None):
    """Restore a checkpoint (``step=None`` → latest COMPLETE step).

    ``template`` is a pytree of arrays or ShapeDtypeStructs with the
    SAME named-tree structure passed to :func:`save_checkpoint`; it
    restores container types (NamedTuples) that serialization flattens.
    Returns the restored dict of trees (plus ``_step``). An explicitly
    requested ``step`` whose commit marker is missing raises — a torn
    save must never be resumed from, even by name.
    """
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory!r}")
    elif (not os.path.exists(_marker_path(directory, step))
          and os.path.isdir(directory)
          and _directory_is_marker_governed(directory)):
        # same legacy fallback as latest_step: only a marker-governed
        # directory treats a marker-less step as torn
        raise FileNotFoundError(
            f"checkpoint step {step} under {directory!r} has no commit "
            f"marker — the save did not finish (torn checkpoint); "
            f"resume from latest_step() instead")
    path = checkpoint_path(directory, step)
    if template is not None:
        item = dict(template)
        item["_step"] = step
        return _checkpointer().restore(path, item=item)
    return _checkpointer().restore(path)


# ---------------------------------------------------------------------------
# TrainState tier: the donated fused-step carry as one named tree
# ---------------------------------------------------------------------------


def save_train_state(directory: str, state) -> str:
    """Save a :class:`~apex_tpu.train.TrainState` (or any pytree with a
    ``.step`` scalar leaf) under ``directory/step_NNNNNNNNN``.

    The state is **host-copied first** (``jax.device_get``): a donated
    state's device buffers are consumed by the next dispatch, so the
    checkpoint must own its memory — and the copy doubles as the sync
    point guaranteeing every dispatched step reflected in ``state``
    has actually executed. A mesh-sharded state (the GSPMD train step)
    lands as plain host-replicated arrays — the payload is
    topology-free — but its mesh shape joins the commit-marker
    fingerprint so :func:`load_train_state` can refuse a mismatched
    mesh instead of silently resharding. Returns the checkpoint
    path."""
    import numpy as np

    mesh_shape = state_mesh_shape(state)
    host = jax.device_get(state)
    step = int(np.asarray(host.step))
    return save_checkpoint(
        directory, step, train_state=host,
        fingerprint={"mesh_shape": mesh_shape} if mesh_shape else None)


def load_train_state(directory: str, template_state,
                     step: Optional[int] = None):
    """Restore a :func:`save_train_state` checkpoint (``step=None`` →
    latest) as ``(state, step)``. ``template_state`` supplies the tree
    structure — a fresh ``TrainStep.init(params)`` result works (its
    values are never read, only its containers/dtypes/shapes). Leaves
    come back as device arrays; resuming a loop from the result is
    bit-identical to the uninterrupted run (tests/test_faults.py).

    **Mesh fingerprint**: when both the checkpoint's commit marker and
    ``template_state`` carry a mesh shape and they differ, the load is
    REFUSED (``ValueError`` naming both shapes) — a (2, 1) shard set
    silently resharded onto a (1, 2) mesh would resume without error
    and train a subtly different program; cross-topology moves must go
    through a meshless template explicitly. Legacy checkpoints (no
    fingerprint in the marker) and meshless templates skip the check.
    Restored leaves are committed onto the template's shardings, so a
    resumed sharded step re-dispatches the already-compiled program
    instead of retracing."""
    import jax.numpy as jnp

    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory!r}")
    marker = read_marker(os.path.abspath(os.fspath(directory)), step)
    saved_mesh = (marker or {}).get("fingerprint", {}).get("mesh_shape")
    want_mesh = state_mesh_shape(template_state)
    if saved_mesh is not None and want_mesh is not None \
            and saved_mesh != want_mesh:
        raise ValueError(
            f"checkpoint step {step} under {directory!r} was saved from "
            f"a mesh of shape {saved_mesh} but the template state is "
            f"sharded over {want_mesh} — refusing to reshard on resume "
            f"(knob: mesh; load into a meshless template and re-shard "
            f"explicitly to move topologies)")
    restored = load_checkpoint(directory, step=step,
                               template=dict(train_state=template_state))

    def _place(x, t):
        x = jnp.asarray(x)
        sharding = getattr(t, "sharding", None)
        if getattr(sharding, "mesh", None) is not None:
            x = jax.device_put(x, sharding)
        return x

    state = jax.tree.map(_place, restored["train_state"], template_state)
    return state, int(restored["_step"])


# ---------------------------------------------------------------------------
# fused-qkv <-> split-q/k/v checkpoint remapping
# ---------------------------------------------------------------------------
#
# The TP attention blocks keep ONE fused qkv projection (Megatron layout:
# [q | k | v] along the output axis of a ColumnParallelLinear named
# "qkv" / "attn_qkv"), while the non-TP blocks use three flat q/k/v
# Dense params (the transpose-free flash-entry layout). Checkpoints are
# therefore NOT layout-portable between TP and non-TP configs; these
# helpers convert a param tree between the two layouts so either kind of
# checkpoint loads into either config.

_QKV_FUSED_NAMES = {"qkv": ("q", "k", "v"),
                    "attn_qkv": ("attn_q", "attn_k", "attn_v")}


def _is_linear_params(v) -> bool:
    return (isinstance(v, dict) and "kernel" in v
            and all(k in ("kernel", "bias") for k in v))


def split_fused_qkv(params, fused_names=None):
    """Rewrite every fused ``qkv`` linear in ``params`` into three
    ``q``/``k``/``v`` linears (split on the last axis, Megatron
    [q | k | v] order). Non-qkv subtrees pass through untouched; the
    input tree is not modified. ``fused_names`` maps fused module name →
    3-tuple of split names (default: ``qkv``→(q,k,v),
    ``attn_qkv``→(attn_q,attn_k,attn_v))."""
    import numpy as np

    fused_names = dict(_QKV_FUSED_NAMES if fused_names is None
                       else fused_names)

    def walk(tree):
        if not isinstance(tree, dict):
            return tree
        out = {}
        for k, v in tree.items():
            if k in fused_names and _is_linear_params(v):
                for i, name in enumerate(fused_names[k]):
                    out[name] = {
                        a: np.split(np.asarray(arr), 3, axis=-1)[i]
                        for a, arr in v.items()}
            else:
                out[k] = walk(v)
        return out

    return walk(params)


def merge_split_qkv(params, fused_names=None):
    """Inverse of :func:`split_fused_qkv`: concatenate ``q``/``k``/``v``
    linears back into one fused ``qkv`` linear (last-axis concat in
    Megatron order). Only merges when all three split names are present
    as linear-param subtrees."""
    import numpy as np

    fused_names = dict(_QKV_FUSED_NAMES if fused_names is None
                       else fused_names)

    def walk(tree):
        if not isinstance(tree, dict):
            return tree
        out = {}
        done = set()
        for fused, names in fused_names.items():
            if all(n in tree and _is_linear_params(tree[n]) for n in names):
                if fused in tree:
                    raise ValueError(
                        f"cannot merge {names} into {fused!r}: the "
                        f"subtree already contains a {fused!r} entry "
                        f"(mixed-layout checkpoint); resolve the "
                        f"collision before merging")
                out[fused] = {
                    a: np.concatenate(
                        [np.asarray(tree[n][a]) for n in names], axis=-1)
                    for a in tree[names[0]]}
                done.update(names)
        for k, v in tree.items():
            if k not in done:
                out[k] = walk(v)
        return out

    return walk(params)
