"""Deterministic fault injection for chaos-testing the dispatch paths.

A production engine's failure story is only as good as its tests, and
failure tests are only as good as their reproducibility: "the decode
dispatch died once under load" is not a regression test. This module
makes faults *data* — a :class:`FaultPlan` is a seeded, declarative
schedule of failures keyed by **call site** (a string like ``"decode"``
or ``"train_step"``) and **call index** at that site, so a chaos run is
exactly as replayable as the bit-deterministic serving/training runs it
attacks (docs/robustness.md).

Four fault kinds, mirroring the ways a dispatch (or its data) dies:

- ``"transient"`` — raise :class:`TransientDispatchError` *instead of*
  running the dispatch: the compile-service tunnel dropped, the runtime
  hiccuped, a retry would succeed. Consumers retry with bounded backoff
  (the engine's ``max_dispatch_retries``, :class:`TrainLoop`'s
  ``max_retries``) and escalate when retries exhaust.
- ``"nan"`` — let the dispatch run, then corrupt the float leaves of
  its output (or hand the flag back to the caller, who knows which
  output is the loss): the silent failure mode — a poisoned batch, a
  numerically-dead layer — that no exception ever surfaces. Consumers
  watch for it (the train loop's non-finite-loss watchdog).
- ``"crash"`` — raise :class:`SimulatedCrash`: process death at a
  chosen step. Nothing catches this (that is the point); tests catch it
  at top level and prove recovery from the last snapshot/checkpoint is
  bit-identical to the uninterrupted run.
- ``"corrupt"`` — silent data corruption (docs/robustness.md, "Data
  integrity"): the call proceeds, and the caller perturbs the artifact
  it owns with a SEEDED deterministic byte/value flip
  (:func:`perturb_payload` / :func:`perturb_json` /
  :func:`perturb_tokens`, keyed by :meth:`FaultPlan.corrupt_seed`).
  Fired at the integrity sites — ``"spill_put"`` / ``"spill_get"``
  (the host spill tier's write/read paths), ``"checkpoint"`` (the
  periodic failover picture), ``"export"`` / ``"import"`` (migration
  records, one fire per record) — where checksum verification must
  catch it, and at ``"decode"``, where it models a flaky chip emitting
  a wrong token (no checksum can catch compute corruption; the fleet's
  determinism cross-check does). The ``"wire"`` site (docs/fleet.md,
  "Process replicas") is the cross-process frame path: ``corrupt``
  there rots one numeric leaf of a received frame and ``transient``
  truncates it (:func:`wire_chaos`), so the parent's
  verify-and-resend loop is exercised without a real flaky pipe —
  only those two kinds are legal at the site
  (:func:`validate_wire_specs`, checked at replica construction the
  way the engine checks its integrity sites).

The plan fires BEFORE the wrapped call for ``transient``/``crash``
(the dispatch never launches, so no donated buffer is consumed and the
caller's retry sees intact state) and AFTER it for ``nan``/``corrupt``.

Determinism: exact-index triggers (``at=``, ``every=``) depend only on
the per-site call count; probabilistic triggers (``prob=``) draw from
one ``random.Random(seed)`` in call order, which is deterministic
whenever the instrumented program's call order is — true for the
serving engine and the train loop by construction.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

_FAULT_KINDS = ("transient", "nan", "crash", "corrupt")
# the cross-process frame path only has two failure modes worth
# modeling — a rotted frame (corrupt) and a torn one (transient, which
# the hook realizes as truncation); "crash" there is just child death
# (SIGKILL the child instead) and "nan" has no float artifact to hit
WIRE_SITE = "wire"
WIRE_FAULT_KINDS = ("transient", "corrupt")


class TransientDispatchError(RuntimeError):
    """An injected (or real) dispatch failure a retry may cure."""


class SimulatedCrash(RuntimeError):
    """Injected process death. Never caught by the engine or the train
    loop — it unwinds the whole driver, exactly like a SIGKILL would,
    and recovery must come from a snapshot/checkpoint."""


class DispatchFailedError(RuntimeError):
    """A dispatch site kept failing after every allotted retry.

    Raised by retrying consumers (not by the plan itself) once backoff
    is exhausted; carries the site and attempt count so the caller can
    quarantine whatever work unit kept poisoning the dispatch."""

    def __init__(self, site: str, attempts: int, last: Exception):
        super().__init__(
            f"dispatch site {site!r} failed {attempts} consecutive "
            f"attempt(s); last error: {type(last).__name__}: {last}")
        self.site = site
        self.attempts = attempts
        self.last = last


def _transient_error_types() -> Tuple[type, ...]:
    """The exception types a retry is allowed to eat: the injected kind
    plus the runtime's real dispatch-failure type (jaxlib's
    XlaRuntimeError when present — the compile-tunnel/runtime errors
    bench.py's retry history was built on)."""
    types: List[type] = [TransientDispatchError]
    try:  # jaxlib >= 0.4: the one runtime-error type PJRT raises
        from jaxlib.xla_extension import XlaRuntimeError  # type: ignore

        types.append(XlaRuntimeError)
    except Exception:  # pragma: no cover - vintage-dependent
        pass
    return tuple(types)


TRANSIENT_ERRORS: Tuple[type, ...] = _transient_error_types()


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One declarative fault rule.

    Fires at ``site`` on call indices listed in ``at`` (0-based), on
    every ``every``-th call (indices ``every-1, 2*every-1, ...``), or
    with probability ``prob`` per call (seeded draw); ``max_fires``
    bounds the total (None = unbounded). A spec with none of the three
    triggers never fires."""

    site: str
    kind: str
    at: Tuple[int, ...] = ()
    every: Optional[int] = None
    prob: float = 0.0
    max_fires: Optional[int] = None

    def __post_init__(self):
        if self.kind not in _FAULT_KINDS:
            raise ValueError(
                f"kind must be one of {_FAULT_KINDS}, got {self.kind!r}")
        if self.every is not None and self.every < 1:
            raise ValueError(f"every must be >= 1, got {self.every}")
        if not 0.0 <= self.prob <= 1.0:
            raise ValueError(f"prob must be in [0, 1], got {self.prob}")
        # tuples survive dataclass frozen-ness; normalize lists for
        # callers who wrote at=[3]
        object.__setattr__(self, "at", tuple(int(i) for i in self.at))


class FaultPlan:
    """A seeded schedule of :class:`FaultSpec` rules.

    Consumers call :meth:`fire` once per guarded call site invocation,
    BEFORE the dispatch: ``transient``/``crash`` rules raise there,
    ``nan`` rules make it return True and the caller corrupts the
    output it knows to be floating-point (or uses :meth:`wrap`, which
    NaN-fills every inexact array leaf). ``fired`` keeps the full audit
    log; ``counts`` aggregates it for assertions.
    """

    def __init__(self, specs: Sequence[FaultSpec], seed: int = 0):
        import random

        self.specs = tuple(specs)
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._calls: Dict[str, int] = {}
        self._spec_fires = [0] * len(self.specs)
        self.fired: List[Tuple[str, str, int]] = []  # (site, kind, index)
        # per site: the call index of the MOST RECENT fire() that hit a
        # "corrupt" spec, reset to None on every call — the one-call
        # window in which corrupt_seed() hands the caller its
        # perturbation key
        self._last_corrupt: Dict[str, Optional[int]] = {}

    def calls(self, site: str) -> int:
        """How many times ``site`` has been guarded so far."""
        return self._calls.get(site, 0)

    def counts(self) -> Dict[str, Dict[str, int]]:
        """``{site: {kind: fire_count}}`` over the whole run."""
        out: Dict[str, Dict[str, int]] = {}
        for site, kind, _ in self.fired:
            out.setdefault(site, {}).setdefault(kind, 0)
            out[site][kind] += 1
        return out

    def fire(self, site: str) -> bool:
        """Advance the site's call counter and apply matching rules.

        Raises for ``transient``/``crash`` hits; returns True when a
        ``nan`` rule hit (the caller owns the corruption). A
        ``corrupt`` hit does NOT raise the flag — it arms
        :meth:`corrupt_seed` for this one call, and the caller applies
        the seeded perturbation to the artifact it owns. Specs are
        scanned in declaration order and a raising hit stops the scan,
        so a later probabilistic spec's RNG draw is skipped on that
        call — keep at most one probabilistic spec per site when you
        need draw-for-draw reproducibility across plan edits."""
        i = self._calls.get(site, 0)
        self._calls[site] = i + 1
        self._last_corrupt[site] = None
        nan_hit = False
        for s_idx, spec in enumerate(self.specs):
            if spec.site != site:
                continue
            if (spec.max_fires is not None
                    and self._spec_fires[s_idx] >= spec.max_fires):
                continue
            hit = i in spec.at
            if not hit and spec.every is not None:
                hit = (i + 1) % spec.every == 0
            if not hit and spec.prob > 0.0:
                hit = self._rng.random() < spec.prob
            if not hit:
                continue
            self._spec_fires[s_idx] += 1
            self.fired.append((site, spec.kind, i))
            if spec.kind == "crash":
                raise SimulatedCrash(
                    f"injected crash at site {site!r} call {i}")
            if spec.kind == "transient":
                raise TransientDispatchError(
                    f"injected transient failure at site {site!r} call {i}")
            if spec.kind == "corrupt":
                # corrupt is its own silent channel, NOT a nan hit:
                # the caller consults corrupt_seed() and applies the
                # seeded perturbation it owns — returning True here
                # would make an unvalidated consumer (the train loop's
                # nan watchdog, wrap()'s NaN-fill) treat corruption as
                # a nan fault
                self._last_corrupt[site] = i
                continue
            nan_hit = True
        return nan_hit

    def corrupt_seed(self, site: str) -> Optional[int]:
        """The deterministic perturbation seed for the MOST RECENT
        :meth:`fire` at ``site`` — ``None`` unless that call hit a
        ``"corrupt"`` spec. Derived from (plan seed, site, call index),
        so a given chaos plan corrupts the same artifact the same way
        on every run (:func:`corruption_seed`)."""
        i = self._last_corrupt.get(site)
        if i is None:
            return None
        return corruption_seed(self.seed, site, i)

    def wrap(self, site: str, fn, corrupt=None):
        """``fn`` guarded by this plan at ``site``. ``corrupt`` maps the
        output on a ``nan`` hit; the default NaN-fills every inexact
        (float/complex) array leaf of the output pytree, leaving integer
        outputs (e.g. sampled token ids) untouched."""
        if corrupt is None:
            corrupt = nan_corrupt

        def guarded(*args, **kwargs):
            nan_hit = self.fire(site)
            out = fn(*args, **kwargs)
            return corrupt(out) if nan_hit else out

        return guarded


def guarded_call(fn, *args, plan: Optional[FaultPlan] = None,
                 site: str = "dispatch", retries: int = 0,
                 backoff_s: float = 0.0, on_retry=None):
    """THE retry policy both dispatch consumers share (the serving
    engine's ``_guarded_dispatch``, :class:`TrainLoop`'s step): fire
    the plan at ``site``, run ``fn(*args)``, retry transient failures
    up to ``retries`` times sleeping ``backoff_s * 2**attempt`` between
    tries (``on_retry(attempt)`` is the caller's counter hook), and
    raise :class:`DispatchFailedError` on exhaustion.
    :class:`SimulatedCrash` is never caught — it is process death.

    Returns ``(result, nan_hit)`` — ``nan_hit`` is the plan's silent-
    corruption flag, for callers that know which output is the loss.
    Retry soundness is the caller's contract: ``fn``'s inputs must be
    intact after a failed attempt (true when the failure precedes
    buffer consumption — injected faults and launch-time errors; a
    consumed donated buffer raises non-transient on the retry and
    propagates)."""
    last = None
    for attempt in range(retries + 1):
        if attempt:
            if on_retry is not None:
                on_retry(attempt)
            if backoff_s > 0.0:
                time.sleep(backoff_s * (2 ** (attempt - 1)))
        try:
            nan_hit = plan.fire(site) if plan is not None else False
            return fn(*args), nan_hit
        except SimulatedCrash:
            raise
        except TRANSIENT_ERRORS as e:
            last = e
    raise DispatchFailedError(site, retries + 1, last)


def corruption_seed(plan_seed: int, site: str, index: int) -> int:
    """The perturbation key of one ``"corrupt"`` fire: a pure function
    of (plan seed, site, per-site call index), so corruption is as
    replayable as the schedule it attacks."""
    import hashlib

    digest = hashlib.sha256(
        f"{int(plan_seed)}:{site}:{int(index)}".encode("ascii")).digest()
    return int.from_bytes(digest[:4], "big")


def perturb_payload(payload, seed: int):
    """Deterministically flip ONE byte of one array in a numpy payload
    dict (the spill/transport corruption model: a bit flip in host RAM
    after the checksum was taken). Returns a NEW dict — only the
    touched array is copied; non-array values pass through."""
    import numpy as np

    keys = sorted(k for k, v in payload.items()
                  if isinstance(v, np.ndarray) and v.nbytes > 0)
    out = dict(payload)
    if not keys:
        return out
    rng = np.random.RandomState(seed & 0xFFFFFFFF)
    k = keys[rng.randint(len(keys))]
    a = np.array(payload[k], copy=True)
    flat = a.view(np.uint8).reshape(-1)
    flat[rng.randint(flat.size)] ^= np.uint8(1 + rng.randint(255))
    out[k] = a
    return out


def perturb_json(obj, seed: int):
    """Deterministically perturb ONE numeric leaf of a JSON-able tree
    (the record/checkpoint corruption model). Deep-copies via the JSON
    round trip the artifact would ride anyway; bool leaves are left
    alone (they encode as ``true``/``false``, not numbers). A tree
    with no numeric leaf comes back unchanged."""
    import json
    import random

    out = json.loads(json.dumps(obj))
    leaves = []

    def walk(node, container, key):
        if isinstance(node, dict):
            for k in sorted(node):
                walk(node[k], node, k)
        elif isinstance(node, list):
            for i, v in enumerate(node):
                walk(v, node, i)
        elif isinstance(node, (int, float)) and not isinstance(node, bool):
            leaves.append((container, key))

    walk(out, None, None)
    if leaves:
        rng = random.Random(seed)
        container, key = leaves[rng.randrange(len(leaves))]
        delta = 1 + rng.randrange(997)
        container[key] = container[key] + delta
    return out


def perturb_tokens(tokens, counts, vocab_size: int, seed: int):
    """Deterministically corrupt ONE emitted token of a drained decode
    batch — the silent-data-corruption model: a flaky chip computed a
    wrong (but in-vocabulary) token id. ``tokens`` is the fetched
    ``[B, K]`` int array, ``counts`` the per-lane valid-token counts;
    the perturbed copy is returned (unchanged when no lane emitted
    anything). The replacement differs from the original by
    construction and stays in ``[0, vocab_size)`` — nothing downstream
    can tell it from a legitimately-sampled token, which is the
    point."""
    import numpy as np

    tokens = np.array(tokens, copy=True)
    lanes = [i for i in range(tokens.shape[0]) if counts[i] > 0]
    if not lanes or vocab_size < 2:
        return tokens
    rng = np.random.RandomState(seed & 0xFFFFFFFF)
    lane = lanes[rng.randint(len(lanes))]
    pos = rng.randint(int(counts[lane]))
    old = int(tokens[lane, pos])
    tokens[lane, pos] = (old + 1 + rng.randint(vocab_size - 1)) \
        % vocab_size
    return tokens


def validate_wire_specs(specs: Sequence[FaultSpec]) -> None:
    """Construction-time validation of ``"wire"``-site rules: only
    :data:`WIRE_FAULT_KINDS` are legal there (the same discipline the
    engine applies to its integrity sites) — a plan wiring ``crash``
    or ``nan`` at the frame path is a test bug, surfaced at replica
    construction instead of silently never firing."""
    for spec in specs:
        if spec.site == WIRE_SITE and spec.kind not in WIRE_FAULT_KINDS:
            raise ValueError(
                f"fault kind {spec.kind!r} is not valid at site "
                f"{WIRE_SITE!r}; legal kinds: {WIRE_FAULT_KINDS} "
                "(SIGKILL the child to model a crash)")


def wire_chaos(plan: FaultPlan):
    """The parent-side frame chaos hook: a ``bytes -> bytes`` callable
    for ``wire.read_frame(chaos=...)``, firing ``plan`` at the
    ``"wire"`` site once per received frame. A ``transient`` hit
    truncates the body to half (a torn frame — the reader's JSON parse
    fails with an ``IntegrityError``); a ``corrupt`` hit perturbs one
    numeric leaf via :func:`perturb_json` and re-encodes (the embedded
    checksum goes stale — ``verify_record`` refuses). Either way the
    full frame already left the pipe, so the simulated damage never
    desyncs the stream — the parent's resend of the SAME request id
    exercises the real retry/dedupe path."""
    validate_wire_specs(plan.specs)

    def hook(body: bytes) -> bytes:
        import json

        try:
            plan.fire(WIRE_SITE)
        except TransientDispatchError:
            return body[: len(body) // 2]
        seed = plan.corrupt_seed(WIRE_SITE)
        if seed is not None:
            rec = perturb_json(json.loads(body.decode("utf-8")), seed)
            return json.dumps(rec, separators=(",", ":")).encode("utf-8")
        return body

    return hook


def spec_record(spec: FaultSpec) -> Dict:
    """One :class:`FaultSpec` as a JSON-able record — the shape a
    fault plan rides to a child replica process in (docs/fleet.md,
    "Process replicas")."""
    return {
        "site": spec.site,
        "kind": spec.kind,
        "at": list(spec.at),
        "every": spec.every,
        "prob": spec.prob,
        "max_fires": spec.max_fires,
    }


def plan_record(plan: FaultPlan) -> Dict:
    """A FRESH plan's declarative content (seed + specs) as a
    JSON-able record. Runtime state (call counters, the audit log) is
    deliberately not carried: the receiver reconstructs an unfired
    plan, which is the only thing it makes sense to ship."""
    return {"seed": plan.seed,
            "specs": [spec_record(s) for s in plan.specs]}


def plan_from_record(rec: Dict) -> FaultPlan:
    """Invert :func:`plan_record` — ``FaultSpec.__post_init__``
    re-validates every rule, so a rotted record fails loudly here."""
    specs = [FaultSpec(site=s["site"], kind=s["kind"],
                       at=tuple(s.get("at") or ()),
                       every=s.get("every"),
                       prob=float(s.get("prob") or 0.0),
                       max_fires=s.get("max_fires"))
             for s in rec.get("specs", ())]
    return FaultPlan(specs, seed=int(rec.get("seed", 0)))


def split_plan(plan: Optional[FaultPlan], site: str
               ) -> Tuple[Optional[FaultPlan], Optional[FaultPlan]]:
    """Partition a plan into ``(at_site, elsewhere)`` sub-plans (same
    seed, None where empty): the router keeps the ``"wire"`` rules on
    its side of the pipe and ships the rest to the child, so one chaos
    plan still describes the whole replica."""
    if plan is None:
        return None, None
    here = [s for s in plan.specs if s.site == site]
    there = [s for s in plan.specs if s.site != site]
    return (FaultPlan(here, seed=plan.seed) if here else None,
            FaultPlan(there, seed=plan.seed) if there else None)


def nan_corrupt(tree):
    """NaN-fill every inexact array leaf of ``tree`` (the default
    ``nan`` corruption): the shape/dtype-preserving analog of a batch
    whose activations went non-finite."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def leaf(x):
        if hasattr(x, "dtype") and jnp.issubdtype(
                np.dtype(x.dtype), np.inexact):
            return jnp.full(jnp.shape(x), jnp.nan, x.dtype)
        return x

    return jax.tree.map(leaf, tree)
