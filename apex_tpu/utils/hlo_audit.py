"""HLO collective audit: count synchronization ops/bytes in compiled HLO.

The round-4 DDP bytes-ratio metric proved the value of auditing the
COMPILED program instead of wall-clock on a shared-core virtual mesh:
a silently duplicated collective is invisible to correctness tests and
to CPU-sim timing, but is exactly countable in HLO text. Round 5
generalizes that machinery from all-reduce-only to the full collective
set (VERDICT r4 weak #4 / next #4, advisor r4 finding #3: a regression
that replaces an all-reduce with a reduce-scatter + all-gather pair
must not read as "fewer bytes"), and wires audits into the multichip
dryrun for TP/PP, ring/Ulysses CP, and ZeRO steps.

Byte accounting: for each collective op we sum the OUTPUT-shape bytes
(all shapes for tuple-typed ops). That is the payload a backend must
materialize per op instance; for loop-body collectives (e.g. the ring's
scan) the static HLO op is counted once, not per trip — counts are a
program-shape invariant, not a traffic simulation. Comparisons must
therefore use the same accounting on both sides, which every in-repo
caller does.
"""

from __future__ import annotations

import re
import warnings
from typing import Dict

_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8,
                "s32": 4, "u64": 8, "u32": 4, "s16": 2, "u16": 2,
                "s8": 1, "u8": 1, "pred": 1,
                # complex payloads (FFT-adjacent collectives)
                "c64": 8, "c128": 16}

# HLO op mnemonics of the cross-device collective set (async variants
# appear as <op>-start / <op>-done; only -start carries the shapes we
# count, and sync forms have no suffix).
COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

_LINE_RE = re.compile(
    r"=\s*(?P<shapes>.*?)\s+(?P<kind>"
    + "|".join(COLLECTIVE_KINDS)
    + r")(?:-start)?\((?P<operands>[^)]*)")

# an instruction defined as broadcast of a SCALAR (empty dims `[]`) —
# its value is sharding-invariant by construction, so any collective
# whose operands are all such broadcasts moves no information
_SCALAR_BCAST_RE = re.compile(
    r"%(?P<name>[\w.\-]+)\s*=\s*\S+\s*broadcast\(\s*[a-z][a-z0-9]*\[\]")


def _dtype_bytes(dt: str) -> int:
    """Element size for an HLO dtype mnemonic. Unknown dtypes WARN and
    fall back to 4 bytes — a silent default miscounted c64/c128/f8
    payloads (advisor r5 #2); the warning makes a new XLA dtype a
    visible one-line fix instead of a quietly wrong audit."""
    if dt in _DTYPE_BYTES:
        return _DTYPE_BYTES[dt]
    if dt.startswith("f8") or dt.startswith("f4"):
        return 1  # every f8 flavor (e4m3/e5m2/...) is one byte; f4 sub-byte
    warnings.warn(
        f"hlo_audit: unknown HLO dtype {dt!r}; assuming 4 bytes — add it "
        f"to _DTYPE_BYTES for exact accounting", stacklevel=3)
    return 4


def _shape_bytes(shapes_text: str) -> int:
    total = 0
    for dt, dims in re.findall(r"\b([a-z][a-z0-9]*)\[([\d,]*)\]",
                               shapes_text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _dtype_bytes(dt)
    return total


def collective_stats(hlo_text: str,
                     exclude_degenerate: bool = False,
                     ) -> Dict[str, Dict[str, int]]:
    """Per-kind ``{"ops": count, "bytes": output_bytes}`` for every
    collective in ``hlo_text``, plus a ``"total"`` row. Async pairs
    are counted once (the ``-done`` line repeats no shapes and does
    not match).

    ``exclude_degenerate=True`` drops collectives whose every operand
    is a broadcast of a scalar, tallying them under a separate
    ``"degenerate"`` row instead of their kind (and outside the
    total). XLA's CSE merges the scalar-constant broadcasts (optimizer
    betas, ``1/accum`` divisors, zero fills) shared by same-shape
    leaves committed to DIFFERENT layouts, then "reshards" the merged
    broadcast with a collective — an all-to-all of a constant that
    moves no model or optimizer data. The sharded train step's
    contract forbids all-to-all of real data; these artifacts would be
    false positives. Default ``False`` keeps the raw count (the
    serving audits' historical accounting)."""
    stats = {k: {"ops": 0, "bytes": 0} for k in COLLECTIVE_KINDS}
    scalar_bcasts = (
        {m.group("name") for m in _SCALAR_BCAST_RE.finditer(hlo_text)}
        if exclude_degenerate else set())
    degenerate = {"ops": 0, "bytes": 0}
    for line in hlo_text.splitlines():
        m = _LINE_RE.search(line)
        if not m:
            continue
        kind = m.group("kind")
        nbytes = _shape_bytes(m.group("shapes"))
        if exclude_degenerate:
            operands = re.findall(r"%([\w.\-]+)", m.group("operands"))
            if operands and all(op in scalar_bcasts for op in operands):
                degenerate["ops"] += 1
                degenerate["bytes"] += nbytes
                continue
        stats[kind]["ops"] += 1
        stats[kind]["bytes"] += nbytes
    stats["total"] = {
        "ops": sum(s["ops"] for s in stats.values()),
        "bytes": sum(s["bytes"] for s in stats.values()),
    }
    if exclude_degenerate:
        stats["degenerate"] = degenerate
    return stats


def abstract_sharded(tree):
    """Mirror a pytree of (possibly committed, possibly donated) arrays
    as ``jax.ShapeDtypeStruct`` leaves carrying each array's sharding —
    the input for ``jitted.lower(...)`` audits. Lowering from abstract
    sharded structs compiles the exact per-mesh program WITHOUT
    dispatching it or consuming donated buffers, and leaves the jit
    call cache untouched (the serving engine's AOT audit pattern,
    generalized). Non-array leaves (plain ints in NamedTuple slots)
    pass through unchanged."""
    import jax

    def one(x):
        if hasattr(x, "ndim") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(
                tuple(x.shape), x.dtype,
                sharding=getattr(x, "sharding", None))
        return x

    return jax.tree.map(one, tree)


def lowered_collective_stats(jitted, *args, **kwargs):
    """Compile ``jitted`` for ``args`` and return
    :func:`collective_stats` of the optimized HLO."""
    return collective_stats(
        jitted.lower(*args, **kwargs).compile().as_text())


_ALIAS_ENTRY_RE = re.compile(
    r"\{(?P<out>[\d,\s]*)\}:\s*\((?P<param>\d+),\s*\{(?P<pidx>[\d,\s]*)\},"
    r"\s*(?P<kind>may-alias|must-alias)\)")


def input_output_alias_stats(hlo_text: str) -> Dict:
    """Donation audit: parse the ``input_output_alias`` table of compiled
    HLO into ``{"pairs": N, "params": sorted-param-numbers, "kinds":
    {...}, "entries": [...]}``.

    XLA DROPS a requested donation silently (a one-line warning at
    best) when an output's layout/shape/dtype doesn't match the donated
    input — the program still runs, just with a transient second copy
    of every parameter and optimizer moment. A fused train step whose
    whole point is in-place aliased updates therefore needs a POSITIVE
    signal from the compiled program, not the absence of an error: this
    counter is that signal (``pairs >= expected`` in tests), the
    aliasing analog of :func:`collective_stats`.
    """
    entries = []
    marker = "input_output_alias={"
    start = hlo_text.find(marker)
    if start >= 0:
        # scan to the matching close brace (entries contain nested {})
        i = start + len(marker)
        depth = 1
        while i < len(hlo_text) and depth:
            if hlo_text[i] == "{":
                depth += 1
            elif hlo_text[i] == "}":
                depth -= 1
            i += 1
        section = hlo_text[start + len(marker):i - 1]
        for m in _ALIAS_ENTRY_RE.finditer(section):
            entries.append({
                "output_index": m.group("out").strip(),
                "param_number": int(m.group("param")),
                "kind": m.group("kind"),
            })
    kinds: Dict[str, int] = {}
    for e in entries:
        kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
    return {
        "pairs": len(entries),
        "params": sorted({e["param_number"] for e in entries}),
        "kinds": kinds,
        "entries": entries,
    }


def lowered_alias_stats(jitted, *args, **kwargs) -> Dict:
    """Compile ``jitted`` for ``args`` and return
    :func:`input_output_alias_stats` of the optimized HLO."""
    return input_output_alias_stats(
        jitted.lower(*args, **kwargs).compile().as_text())


def assert_collective_contract(stats: Dict[str, Dict[str, int]],
                               exact_total_ops: int = None,
                               min_ops: Dict[str, int] = None,
                               alt_min_ops: Dict[str, int] = None,
                               forbidden=(),
                               label: str = "program") -> None:
    """Check a program-shape collective contract against
    :func:`collective_stats` output, raising ``AssertionError`` with
    the full per-kind table on any violation — the serving engine's
    sharded-program audit (docs/serving.md "Mesh sharding";
    ``apex_tpu.serving.mesh.expected_collectives`` builds the expected
    kwargs per mesh shape).

    - ``exact_total_ops``: the total op count must equal this (0 is
      the single-partition contract: a program that must lower
      collective-free).
    - ``min_ops``: per-kind op-count floors that must ALL hold — or,
      when ``alt_min_ops`` is given, the alternative set may hold
      instead (XLA legitimately lowers one all-reduce as a
      reduce-scatter + all-gather pair; either spelling satisfies the
      reduction contract, and hlo_audit's own round-5 lesson is that
      the two must be counted as equivalent, not compared raw).
    - ``forbidden``: kinds whose op count must be zero.
    """
    table = {k: v["ops"] for k, v in stats.items() if k != "total"}
    total = stats.get("total", {}).get("ops", sum(table.values()))
    if exact_total_ops is not None and total != exact_total_ops:
        raise AssertionError(
            f"{label}: expected exactly {exact_total_ops} collective "
            f"op(s), compiled program has {total} ({table})")
    for kind in forbidden:
        if stats.get(kind, {}).get("ops", 0):
            raise AssertionError(
                f"{label}: forbidden collective kind {kind!r} present "
                f"({table})")

    def _meets(floors):
        return all(stats.get(k, {}).get("ops", 0) >= n
                   for k, n in floors.items())

    if min_ops and not _meets(min_ops):
        if not (alt_min_ops and _meets(alt_min_ops)):
            raise AssertionError(
                f"{label}: expected collective floors {min_ops}"
                + (f" (or {alt_min_ops})" if alt_min_ops else "")
                + f" not met by compiled program ({table})")


def format_stats(stats: Dict[str, Dict[str, int]]) -> str:
    """One-line human summary of non-zero kinds (dryrun log format)."""
    parts = [f"{k}:{v['ops']}op/{v['bytes']}B"
             for k, v in stats.items()
             if k != "total" and v["ops"]]
    return " ".join(parts) if parts else "none"
