"""Legacy fp16 helpers (reference: ``apex/fp16_utils/fp16util.py``,
SURVEY.md §2.1).

The reference predates amp: ``network_to_half`` casts a model in place,
``prep_param_lists`` builds (model, fp32 master) parameter pairs, and
``master_params_to_model_params``/``model_grads_to_master_grads`` copy
between them around an fp32 optimizer step. Functionally the same
surface on pytrees — model "halves" are new pytrees, masters are fp32
copies (optionally one flat buffer, the reference's ``flat_master``).

On TPU the native half type is bfloat16, so that is the default
``half_dtype``; pass ``jnp.float16`` for literal fp16 parity.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.utils.pytree import ravel_list, tree_cast, unravel_list


def _ravel_f32(tree):
    """Flatten to one fp32 buffer (the apex_C.flatten analog)."""
    flat, _ = ravel_list(
        [l.astype(jnp.float32) for l in jax.tree.leaves(tree)])
    return flat


def network_to_half(params, half_dtype=jnp.bfloat16):
    """Cast every floating leaf to the half dtype (reference
    ``network_to_half``; BN params are the classic exception there —
    handled by amp's ``keep_batchnorm_fp32``, not this legacy helper)."""
    return tree_cast(params, half_dtype)


def prep_param_lists(params, flat_master: bool = False):
    """Build (model_params, master_params) (reference ``prep_param_lists``).

    ``flat_master=True`` returns the master as ONE flat fp32 vector (the
    reference flattens via ``_flatten_dense_tensors``); otherwise a
    same-structure fp32 pytree.
    """
    if flat_master:
        return params, _ravel_f32(params)
    return params, tree_cast(params, jnp.float32)


def master_params_to_model_params(model_params, master_params,
                                  flat_master: bool = False):
    """Copy master values into the model dtypes (reference name); returns
    the new model pytree (functional — no in-place .data copies)."""
    if flat_master:
        meta = [(l.shape, l.dtype, l.size)
                for l in jax.tree.leaves(model_params)]
        leaves = unravel_list(master_params, meta)
        return jax.tree.unflatten(jax.tree.structure(model_params), leaves)
    return jax.tree.map(lambda mp, m: m.astype(mp.dtype),
                        model_params, master_params)


def model_grads_to_master_grads(model_grads, flat_master: bool = False):
    """Cast model grads to fp32 master grads (reference name)."""
    if flat_master:
        return _ravel_f32(model_grads)
    return tree_cast(model_grads, jnp.float32)


def to_python_float(t) -> float:
    """Reference helper: scalar device value → host float."""
    return float(jax.device_get(t))
