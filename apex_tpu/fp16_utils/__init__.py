"""apex.fp16_utils parity surface (reference: ``apex/fp16_utils``)."""

from apex_tpu.amp.scaler import DynamicLossScaler, LossScaler
from apex_tpu.fp16_utils.fp16_optimizer import FP16OptState, FP16_Optimizer
from apex_tpu.fp16_utils.fp16util import (
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
    to_python_float,
)

__all__ = [
    "DynamicLossScaler",
    "FP16OptState",
    "FP16_Optimizer",
    "LossScaler",
    "master_params_to_model_params",
    "model_grads_to_master_grads",
    "network_to_half",
    "prep_param_lists",
    "to_python_float",
]
