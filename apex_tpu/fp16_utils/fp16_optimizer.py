"""Legacy ``FP16_Optimizer`` (reference:
``apex/fp16_utils/fp16_optimizer.py``, SURVEY.md §2.1).

The reference wraps any torch optimizer: it keeps fp32 master params,
scales the loss (static or ``DynamicLossScaler``), copies model grads to
master fp32 grads, unscales, skips the step on overflow, and copies
updated masters back into the fp16 model. That is exactly the amp-O2
data flow, so this class is a thin veneer over the same pieces the amp
path uses: ``LossScaler`` (identical constants) + a wrapped
``apex_tpu.optimizers`` fused optimizer with ``master_weights``.

Functional contract (the torch version mutates ``.grad``/``.data``)::

    opt = FP16_Optimizer(FusedSGD(lr=1e-2), dynamic_loss_scale=True)
    state = opt.init(params_half)
    scaled = opt.scale_loss(loss, state)        # or scaler.value_and_grad
    params, state, skipped = opt.step(grads_half, state, params_half)

``skipped`` mirrors the reference's overflow bookkeeping
(``optimizer.overflow`` attribute after ``step``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Union

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler, ScalerState
from apex_tpu.fp16_utils.fp16util import model_grads_to_master_grads


class FP16OptState(NamedTuple):
    inner: Any           # wrapped optimizer state (holds fp32 masters)
    scaler: ScalerState


@dataclasses.dataclass(frozen=True)
class FP16_Optimizer:
    """Reference constructor shape: ``FP16_Optimizer(init_optimizer,
    static_loss_scale=1.0, dynamic_loss_scale=False,
    dynamic_loss_args=None, verbose=True)``."""

    init_optimizer: Any
    static_loss_scale: float = 1.0
    dynamic_loss_scale: bool = False
    verbose: bool = True  # parity knob; logging rides amp's gates

    def __post_init__(self):
        inner = self.init_optimizer.with_master_weights(True)
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(
            self, "_scaler",
            LossScaler("dynamic" if self.dynamic_loss_scale
                       else float(self.static_loss_scale)))

    @property
    def optimizer(self):
        """The wrapped fused optimizer (reference attribute name)."""
        return self._inner

    @property
    def loss_scaler(self) -> LossScaler:
        return self._scaler

    def loss_scale(self, state: FP16OptState) -> jnp.ndarray:
        return state.scaler.loss_scale

    def init(self, params) -> FP16OptState:
        return FP16OptState(
            inner=self._inner.init(params),
            scaler=self._scaler.init(),
        )

    def scale_loss(self, loss, state: FP16OptState):
        """Reference ``optimizer.backward(loss)`` scales the loss before
        autodiff; functionally: scale the loss value (use inside your
        loss fn, or use ``loss_scaler.value_and_grad``)."""
        return self._scaler.scale(loss, state.scaler)

    def step(self, grads, state: FP16OptState, params, lr=None):
        """Unscale → overflow check → (maybe) fused master step → new
        model params. Returns ``(params, state, skipped)`` where
        ``skipped`` is the traced overflow bool (reference
        ``optimizer.overflow``)."""
        master_grads = model_grads_to_master_grads(grads)
        unscaled, found_inf = self._scaler.unscale(
            master_grads, state.scaler)
        new_params, new_inner = self._inner.step(
            unscaled, state.inner, params, skip_if=found_inf, lr=lr)
        new_scaler = self._scaler.update(state.scaler, found_inf)
        return new_params, FP16OptState(new_inner, new_scaler), found_inf

    # reference state_dict surface: the scaler + step counters round-trip
    def state_dict(self, state: FP16OptState):
        return {
            "loss_scaler": {
                "loss_scale": state.scaler.loss_scale,
                "unskipped": state.scaler.unskipped,
                "steps_skipped": state.scaler.steps_skipped,
                "hysteresis": state.scaler.hysteresis,
            },
            "optimizer_state": state.inner,
        }

    def load_state_dict(self, sd) -> FP16OptState:
        return FP16OptState(
            inner=sd["optimizer_state"],
            scaler=ScalerState(
                loss_scale=jnp.asarray(sd["loss_scaler"]["loss_scale"],
                                       jnp.float32),
                unskipped=jnp.asarray(sd["loss_scaler"]["unskipped"],
                                      jnp.int32),
                steps_skipped=jnp.asarray(
                    sd["loss_scaler"]["steps_skipped"], jnp.int32),
                hysteresis=jnp.asarray(
                    sd["loss_scaler"].get("hysteresis",
                                          self._scaler.hysteresis),
                    jnp.int32),
            ),
        )
