"""apex_tpu.optimizers — fused optimizers (SURVEY.md §2.1 L3).

Each optimizer's whole update runs as one XLA flat-buffer fusion via
``multi_tensor_applier`` (see apex_tpu.ops.multi_tensor), mirroring the
reference's one-kernel-launch property on TPU.
"""

from apex_tpu.optimizers.fused_adagrad import AdagradState, FusedAdagrad  # noqa: F401
from apex_tpu.optimizers.fused_adam import AdamState, FusedAdam  # noqa: F401
from apex_tpu.optimizers.fused_lamb import (  # noqa: F401
    FusedLAMB,
    FusedMixedPrecisionLamb,
    LambState,
)
from apex_tpu.optimizers.fused_novograd import FusedNovoGrad, NovoGradState  # noqa: F401
from apex_tpu.optimizers.fused_sgd import FusedSGD, SGDState  # noqa: F401
