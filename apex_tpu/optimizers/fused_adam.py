"""FusedAdam — single fused update over all parameters.

Rebuild of ``apex/optimizers/fused_adam.py`` + ``csrc/multi_tensor_adam.cu``
(SURVEY.md §3.3): the entire Adam/AdamW update for every parameter tensor
runs as one ``multi_tensor_adam`` call — per-leaf fp32 math that XLA fuses
into a handful of HBM-bound passes inside the jitted step, the TPU analog
of the reference's one-kernel-launch step. Knob parity: ``bias_correction``,
``betas``, ``eps``, ``adam_w_mode``, ``weight_decay``, ``amsgrad``
(rejected, like the reference), ``master_weights`` (fp32 masters for amp
O2), ``capturable`` (accepted and ignored: every jitted step is
"capturable" on XLA by construction).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops.multi_tensor import (
    ADAM_MODE_ADAMW,
    ADAM_MODE_L2,
    multi_tensor_adam,
)
from apex_tpu.optimizers._base import FusedOptimizer, leaves_of, like_tree


class AdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: any
    exp_avg_sq: any
    master: any  # fp32 master params pytree, or None


@dataclasses.dataclass(frozen=True)
class FusedAdam(FusedOptimizer):
    """``moments_dtype="bfloat16"`` (round-5 opt-in, default fp32 =
    exact reference parity) stores m/v in bf16 with stochastic rounding
    (unbiased EMAs — see FusedLAMB's docstring for the stall physics),
    halving the optimizer-state HBM traffic and footprint."""

    lr: float = 1e-3
    bias_correction: bool = True
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    adam_w_mode: bool = True
    weight_decay: float = 0.0
    amsgrad: bool = False
    set_grad_none: bool = True  # parity knob; grads are inputs here
    capturable: bool = False
    master_weights: bool = False
    moments_dtype: str = "float32"
    stochastic_rounding: bool = True  # applies when moments_dtype=bf16

    def __post_init__(self):
        if self.amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        self._validate_moments_dtype()

    def init(self, params) -> AdamState:
        mdt = self._moments_dtype
        zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, mdt), params)
        zeros2 = jax.tree.map(lambda p: jnp.zeros(p.shape, mdt), params)
        return AdamState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=zeros,
            exp_avg_sq=zeros2,
            master=self._master_init(params),
        )

    def step(self, grads, state: AdamState, params, skip_if=None, lr=None):
        lr = self.lr if lr is None else lr
        step = state.step + 1

        g = leaves_of(grads)
        p = leaves_of(params)
        m = leaves_of(state.exp_avg)
        v = leaves_of(state.exp_avg_sq)
        lists = [g, p, m, v]
        if self.master_weights:
            lists.append(leaves_of(state.master))

        sr_key = self._sr_key(step, 0xADA3)
        out = multi_tensor_applier(
            multi_tensor_adam,
            None,
            lists,
            lr,
            self.betas[0],
            self.betas[1],
            self.eps,
            step,
            ADAM_MODE_ADAMW if self.adam_w_mode else ADAM_MODE_L2,
            self.bias_correction,
            self.weight_decay,
            sr_key=sr_key,
        )
        new_p = like_tree(out[0], params)
        new_state = AdamState(
            step=step,
            exp_avg=like_tree(out[1], state.exp_avg),
            exp_avg_sq=like_tree(out[2], state.exp_avg_sq),
            master=like_tree(out[3], state.master) if self.master_weights else None,
        )
        return self._finish_step(skip_if, new_p, new_state, params, state)
