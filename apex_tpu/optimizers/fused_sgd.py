"""FusedSGD — fused momentum SGD.

Rebuild of ``apex/optimizers/fused_sgd.py`` + ``csrc/multi_tensor_sgd_kernel.cu``
(SURVEY.md §2.1): params/momentum for every tensor updated in one
flat-buffer fusion. Knob parity: ``momentum``, ``dampening``, ``nesterov``
(with the reference's validity check), ``weight_decay``,
``wd_after_momentum``, ``materialize_master_grads`` (parity no-op: grads
are always materialized inputs here), ``master_weights``, and the
``scale`` pre-factor used by amp integration.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops.multi_tensor import multi_tensor_sgd
from apex_tpu.optimizers._base import FusedOptimizer, leaves_of, like_tree


class SGDState(NamedTuple):
    step: jnp.ndarray
    momentum_buffer: any
    master: any


@dataclasses.dataclass(frozen=True)
class FusedSGD(FusedOptimizer):
    lr: float = 1e-3  # reference requires lr; keep a sane default
    momentum: float = 0.0
    dampening: float = 0.0
    weight_decay: float = 0.0
    nesterov: bool = False
    wd_after_momentum: bool = False
    materialize_master_grads: bool = True
    master_weights: bool = False

    def __post_init__(self):
        if self.nesterov and (self.momentum <= 0 or self.dampening != 0):
            raise ValueError("Nesterov momentum requires a momentum and zero dampening")

    def init(self, params) -> SGDState:
        return SGDState(
            step=jnp.zeros((), jnp.int32),
            momentum_buffer=jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            ),
            master=self._master_init(params),
        )

    def step(self, grads, state: SGDState, params, skip_if=None, lr=None, scale=1.0):
        lr = self.lr if lr is None else lr
        step = state.step + 1

        g = leaves_of(grads)
        p = leaves_of(params)
        mom = leaves_of(state.momentum_buffer)
        lists = [g, p, mom]
        if self.master_weights:
            lists.append(leaves_of(state.master))

        out = multi_tensor_applier(
            multi_tensor_sgd,
            None,
            lists,
            self.weight_decay,
            self.momentum,
            self.dampening,
            lr,
            self.nesterov,
            state.step == 0,  # first_run: momentum buffer takes the raw grad
            self.wd_after_momentum,
            scale,
        )
        new_p = like_tree(out[0], params)
        new_state = SGDState(
            step=step,
            momentum_buffer=like_tree(out[1], state.momentum_buffer),
            master=like_tree(out[2], state.master) if self.master_weights else None,
        )
        return self._finish_step(skip_if, new_p, new_state, params, state)
