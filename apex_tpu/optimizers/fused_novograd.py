"""FusedNovoGrad — fused NovoGrad with per-tensor second moments.

Rebuild of ``apex/optimizers/fused_novograd.py`` +
``csrc/multi_tensor_novograd.cu`` (SURVEY.md §2.1): the second moment is a
scalar per tensor (the squared-gradient L2 norm EMA), normalizing each
layer's gradient before the first-moment EMA. Knob parity:
``bias_correction``, ``betas``, ``eps``, ``weight_decay``,
``grad_averaging``, ``norm_type`` (2 only, like the reference kernel),
``init_zero``.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops.multi_tensor import multi_tensor_novograd
from apex_tpu.optimizers._base import FusedOptimizer, leaves_of, like_tree


class NovoGradState(NamedTuple):
    step: jnp.ndarray
    exp_avg: any
    exp_avg_sq: jnp.ndarray  # stacked per-tensor scalars, shape (n_tensors,)
    master: any


@dataclasses.dataclass(frozen=True)
class FusedNovoGrad(FusedOptimizer):
    lr: float = 1e-3
    bias_correction: bool = True
    betas: Tuple[float, float] = (0.95, 0.98)
    eps: float = 1e-8
    weight_decay: float = 0.0
    amsgrad: bool = False
    reg_inside_moment: bool = False
    grad_averaging: bool = True
    norm_type: int = 2
    init_zero: bool = False
    set_grad_none: bool = True
    master_weights: bool = False

    def __post_init__(self):
        if self.amsgrad:
            raise RuntimeError("FusedNovoGrad does not support the AMSGrad variant.")
        if self.norm_type != 2:
            raise RuntimeError("FusedNovoGrad only supports the L2 norm_type, like the reference kernel.")

    def init(self, params) -> NovoGradState:
        n = len(leaves_of(params))
        return NovoGradState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            exp_avg_sq=jnp.zeros((n,), jnp.float32),
            master=self._master_init(params),
        )

    def step(self, grads, state: NovoGradState, params, skip_if=None, lr=None):
        lr = self.lr if lr is None else lr
        step = state.step + 1

        g = leaves_of(grads)
        p = leaves_of(params)
        m = leaves_of(state.exp_avg)
        lists = [g, p, m, state.exp_avg_sq]
        if self.master_weights:
            lists.append(leaves_of(state.master))

        out = multi_tensor_applier(
            multi_tensor_novograd,
            None,
            lists,
            lr,
            self.betas[0],
            self.betas[1],
            self.eps,
            step,
            self.bias_correction,
            self.weight_decay,
            self.grad_averaging,
            self.norm_type,
            self.init_zero,
        )
        new_p = like_tree(out[0], params)
        new_state = NovoGradState(
            step=step,
            exp_avg=like_tree(out[1], state.exp_avg),
            exp_avg_sq=out[2],
            master=like_tree(out[3], state.master) if self.master_weights else None,
        )
        return self._finish_step(skip_if, new_p, new_state, params, state)
