"""FusedAdagrad — fused Adagrad.

Rebuild of ``apex/optimizers/fused_adagrad.py`` +
``csrc/multi_tensor_adagrad.cu`` (SURVEY.md §2.1). Knob parity: ``lr``,
``eps``, ``weight_decay``, ``adagrad_w_mode`` (decoupled weight decay).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops.multi_tensor import (
    ADAM_MODE_ADAMW,
    ADAM_MODE_L2,
    multi_tensor_adagrad,
)
from apex_tpu.optimizers._base import FusedOptimizer, leaves_of, like_tree


class AdagradState(NamedTuple):
    step: jnp.ndarray
    sum: any
    master: any


@dataclasses.dataclass(frozen=True)
class FusedAdagrad(FusedOptimizer):
    lr: float = 1e-2
    eps: float = 1e-10
    weight_decay: float = 0.0
    adagrad_w_mode: bool = False
    set_grad_none: bool = True
    master_weights: bool = False

    def init(self, params) -> AdagradState:
        return AdagradState(
            step=jnp.zeros((), jnp.int32),
            sum=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            master=self._master_init(params),
        )

    def step(self, grads, state: AdagradState, params, skip_if=None, lr=None):
        lr = self.lr if lr is None else lr
        step = state.step + 1

        lists = [leaves_of(grads), leaves_of(params), leaves_of(state.sum)]
        if self.master_weights:
            lists.append(leaves_of(state.master))
        out = multi_tensor_applier(
            multi_tensor_adagrad,
            None,
            lists,
            lr,
            self.eps,
            ADAM_MODE_ADAMW if self.adagrad_w_mode else ADAM_MODE_L2,
            self.weight_decay,
        )
        new_p = like_tree(out[0], params)
        new_state = AdagradState(
            step=step,
            sum=like_tree(out[1], state.sum),
            master=like_tree(out[2], state.master) if self.master_weights else None,
        )
        return self._finish_step(skip_if, new_p, new_state, params, state)
