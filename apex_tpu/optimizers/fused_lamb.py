"""FusedLAMB — two-stage fused LAMB (the BERT-large north-star optimizer).

Rebuild of ``apex/optimizers/fused_lamb.py`` (SURVEY.md §3.3): stage 1
computes the global gradient norm (``multi_tensor_l2norm``), clips, and
updates moments into per-tensor update directions
(``multi_tensor_lamb_stage_1``); stage 2 computes per-tensor trust ratios
``||p|| / ||update||`` and applies the step
(``multi_tensor_lamb_stage_2``). Knob parity: ``bias_correction``,
``betas``, ``eps``, ``weight_decay``, ``grad_averaging``,
``max_grad_norm``, ``adam_w_mode``, ``use_nvlamb``, ``master_weights``.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops.multi_tensor import (
    multi_tensor_l2norm,
    multi_tensor_lamb_stage1,
    multi_tensor_lamb_stage2,
)
from apex_tpu.optimizers._base import FusedOptimizer, leaves_of, like_tree


class LambState(NamedTuple):
    step: jnp.ndarray
    exp_avg: any
    exp_avg_sq: any
    master: any


@dataclasses.dataclass(frozen=True)
class FusedLAMB(FusedOptimizer):
    """Two-stage fused LAMB.

    ``moments_dtype="bfloat16"`` (round-5, opt-in — default keeps the
    reference's fp32 moments exactly) stores m/v in bf16 with
    stochastic rounding and switches to a recompute-update stage 2:
    instead of materializing a full fp32 update buffer between the
    trust-ratio reduction and the parameter step, stage 2 recomputes
    the update direction from the just-stored bf16 moments. HBM
    traffic per step at BERT-large (367M params, O2 masters) drops
    from ~14.7 GB to ~8.5 GB. Stochastic rounding keeps the bf16 EMAs
    unbiased (a (1-beta2)*g^2 increment below bf16's 8-bit mantissa
    rounds-to-nearest to zero and v stalls; SR preserves it in
    expectation)."""

    lr: float = 1e-3
    bias_correction: bool = True
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-6
    weight_decay: float = 0.01
    amsgrad: bool = False
    adam_w_mode: bool = True
    grad_averaging: bool = True
    set_grad_none: bool = True
    max_grad_norm: float = 1.0
    use_nvlamb: bool = False
    master_weights: bool = False
    moments_dtype: str = "float32"
    stochastic_rounding: bool = True  # applies when moments_dtype=bf16

    def __post_init__(self):
        if self.amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        if not self.adam_w_mode:
            raise RuntimeError(
                "FusedLAMB only supports adam_w_mode (decoupled weight decay), "
                "matching the reference kernel."
            )
        self._validate_moments_dtype()

    def init(self, params) -> LambState:
        mdt = self._moments_dtype
        return LambState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=jax.tree.map(lambda p: jnp.zeros(p.shape, mdt), params),
            exp_avg_sq=jax.tree.map(lambda p: jnp.zeros(p.shape, mdt), params),
            master=self._master_init(params),
        )

    def step(self, grads, state: LambState, params, skip_if=None, lr=None,
             grad_scale=None):
        """One fused LAMB step. ``grad_scale``: when given, ``grads`` are
        LOSS-SCALED by this factor and the step unscales them for free
        inside its own reads (norm rescale + stage-1 pre-scale — no
        separate unscale pass) AND detects overflow from the norm it
        already computes. With ``grad_scale`` the return is
        ``(params, state, found_inf)`` (found_inf is folded into the
        skip); without it, ``(params, state)`` as before."""
        lr = self.lr if lr is None else lr
        step = state.step + 1

        g = leaves_of(grads)
        p_model = leaves_of(params)
        p_src = leaves_of(state.master) if self.master_weights else p_model
        m = leaves_of(state.exp_avg)
        v = leaves_of(state.exp_avg_sq)

        # Stage 0: global grad norm (one fused reduction pass).
        global_norm, _ = multi_tensor_applier(
            multi_tensor_l2norm, None, [g], False
        )
        pre_scale = 1.0
        found_inf = None
        if grad_scale is not None:
            # inf/nan anywhere in the grads surfaces in the raw norm —
            # the amp overflow check rides this existing reduction
            found_inf = jnp.logical_not(jnp.isfinite(global_norm))
            pre_scale = (1.0 / jnp.asarray(grad_scale, jnp.float32))
            global_norm = global_norm * pre_scale
            skip_if = (found_inf if skip_if is None
                       else jnp.logical_or(skip_if, found_inf))

        if self._moments_dtype == jnp.dtype(jnp.bfloat16):
            return self._low_moments_tail(
                g, p_model, p_src, m, v, state, params, global_norm,
                pre_scale, step, lr, skip_if, found_inf)

        # Stage 1: clip + moments + update directions.
        updates, new_m, new_v = multi_tensor_applier(
            multi_tensor_lamb_stage1,
            None,
            [g, p_src, m, v],
            self.betas[0],
            self.betas[1],
            self.eps,
            step,
            self.bias_correction,
            self.weight_decay,
            self.grad_averaging,
            global_norm,
            self.max_grad_norm,
            pre_scale,
        )

        # Stage 2: per-tensor trust ratios + parameter step.
        lists = [p_model, updates]
        if self.master_weights:
            lists.append(p_src)
        out = multi_tensor_applier(
            multi_tensor_lamb_stage2, None, lists, lr, self.weight_decay,
            self.use_nvlamb,
        )
        if self.master_weights:
            new_p_leaves, new_master_leaves = out
            new_master = like_tree(new_master_leaves, state.master)
        else:
            new_p_leaves, new_master = out, None

        new_p = like_tree(new_p_leaves, params)
        new_state = LambState(
            step=step,
            exp_avg=like_tree(new_m, state.exp_avg),
            exp_avg_sq=like_tree(new_v, state.exp_avg_sq),
            master=new_master,
        )
        out_p, out_s = self._finish_step(skip_if, new_p, new_state, params,
                                         state)
        if found_inf is not None:
            return out_p, out_s, found_inf
        return out_p, out_s

    def _low_moments_tail(self, g, p_model, p_src, m, v, state, params,
                          global_norm, pre_scale, step, lr, skip_if,
                          found_inf):
        """bf16-moments stage 1+2 (see class docstring): stochastic-
        rounded bf16 m/v, and a recompute-update stage 2 — no fp32
        update buffer crosses HBM between the trust-ratio reduction and
        the parameter step; the update direction is recomputed from the
        just-stored rounded moments (the norms in stage 1 are taken of
        the SAME rounded-moment update, so the trust ratio matches the
        step exactly)."""
        from apex_tpu.ops.multi_tensor import (
            lamb_scalars,
            lamb_trust_ratio,
            lamb_update_direction,
            stochastic_round,
        )

        b1, b2 = self.betas
        clip, bc1, bc2, beta3 = lamb_scalars(
            b1, b2, step, self.bias_correction, self.grad_averaging,
            global_norm, self.max_grad_norm, pre_scale)
        key = self._sr_key(step, 0x5A17)
        mdt = self._moments_dtype

        def u_of(m_r, v_r, p32):
            return lamb_update_direction(
                m_r.astype(jnp.float32), v_r.astype(jnp.float32), p32,
                bc1, bc2, self.eps, self.weight_decay)

        # Pass A: moments (rounded) + per-tensor ||u||, ||p|| reductions
        new_m, new_v, u_sq, p_sq = [], [], [], []
        for i, (gi, pi, mi, vi) in enumerate(zip(g, p_src, m, v)):
            g32 = gi.astype(jnp.float32) * clip
            p32 = pi.astype(jnp.float32)
            m32 = b1 * mi.astype(jnp.float32) + beta3 * g32
            v32 = b2 * vi.astype(jnp.float32) + (1.0 - b2) * g32 * g32
            if key is not None:
                mo = stochastic_round(m32, mdt, jax.random.fold_in(key, 2 * i))
                vo = stochastic_round(v32, mdt,
                                      jax.random.fold_in(key, 2 * i + 1))
            else:
                mo, vo = m32.astype(mdt), v32.astype(mdt)
            new_m.append(mo)
            new_v.append(vo)
            u32 = u_of(mo, vo, p32)
            u_sq.append(jnp.sum(u32 * u32))
            p_sq.append(jnp.sum(p32 * p32))

        apply_ratio = self.use_nvlamb or self.weight_decay != 0.0
        if apply_ratio:
            ratios = lamb_trust_ratio(jnp.sqrt(jnp.stack(p_sq)),
                                      jnp.sqrt(jnp.stack(u_sq)))
        else:
            ratios = jnp.ones((len(g),), jnp.float32)

        # Pass B: recompute u from the stored rounded moments + step
        new_p, new_master = [], []
        for i, pi in enumerate(p_src):
            p32 = pi.astype(jnp.float32)
            stepped = p32 - lr * ratios[i] * u_of(new_m[i], new_v[i], p32)
            new_p.append(stepped.astype(p_model[i].dtype))
            if self.master_weights:
                new_master.append(stepped)

        new_state = LambState(
            step=step,
            exp_avg=like_tree(new_m, state.exp_avg),
            exp_avg_sq=like_tree(new_v, state.exp_avg_sq),
            master=(like_tree(new_master, state.master)
                    if self.master_weights else None),
        )
        out_p, out_s = self._finish_step(
            skip_if, like_tree(new_p, params), new_state, params, state)
        if found_inf is not None:
            return out_p, out_s, found_inf
        return out_p, out_s


@dataclasses.dataclass(frozen=True)
class FusedMixedPrecisionLamb(FusedLAMB):
    """Reference ``apex/optimizers/fused_mixed_precision_lamb.py`` (U):
    LAMB that keeps fp32 master weights and moments while the model
    (and its gradients) live in a reduced precision — exactly
    ``FusedLAMB(master_weights=True)`` here, since this rebuild's LAMB
    already runs all moment/trust-ratio math in fp32 and casts back to
    the model dtype (``reduced_precision_dtype`` is therefore inferred
    from the params rather than configured). Named alias so reference
    imports resolve."""

    master_weights: bool = True
