"""Shared machinery for the fused optimizers.

The reference optimizers (``apex/optimizers/*``, SURVEY.md §2.1) are
torch ``Optimizer`` subclasses whose ``step()`` makes one
``multi_tensor_applier`` call. The rebuild keeps that shape as a
functional core: each optimizer is an immutable config object with

- ``init(params) -> state``   (state is a pytree: step count + moments
  [+ fp32 master params when ``master_weights``])
- ``step(grads, state, params, skip_if=None, lr=None) -> (params, state)``

``skip_if`` is the amp overflow flag: when True the returned params/state
are the inputs unchanged and the step counter does not advance —
the in-graph equivalent of apex's patched ``optimizer.step()`` no-op on
overflow (SURVEY.md §3.2). ``as_optax()`` adapts any of these to an
``optax.GradientTransformation`` for idiomatic JAX training loops.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.utils.pytree import tree_select


def leaves_of(tree):
    return jax.tree.leaves(tree)


def like_tree(leaves, tree):
    return jax.tree.unflatten(jax.tree.structure(tree), leaves)


@dataclasses.dataclass(frozen=True)
class FusedOptimizer:
    """Base class: config dataclass + functional init/step."""

    lr: float = 1e-3
    weight_decay: float = 0.0
    master_weights: bool = False

    def with_master_weights(self, flag: bool = True):
        """Return a copy with fp32 master weights enabled (used by
        ``amp.initialize`` for O2, reference ``_process_optimizer``)."""
        return dataclasses.replace(self, master_weights=flag)

    def replace(self, **kw):
        return dataclasses.replace(self, **kw)

    # subclasses implement init() and step()

    def _master_init(self, params):
        if not self.master_weights:
            return None

        def to_master(x):
            x = jnp.asarray(x)
            if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != jnp.float32:
                return x.astype(jnp.float32)
            # Already-f32 leaves (keep_batchnorm_fp32 norms) and integer
            # leaves MUST still get their own buffer: astype is a no-op
            # returning the same array, and a donated train state holding
            # (params, master) would then donate one buffer twice — a
            # runtime error on XLA:CPU/PJRT (and on a replicated mesh the
            # non-raising ranks hang at the next collective rendezvous).
            return jnp.array(x, copy=True)

        return jax.tree.map(to_master, params)

    # --- shared bf16-moments machinery (round 5): subclasses exposing a
    # ``moments_dtype`` field share the validation, dtype resolution,
    # and per-step stochastic-rounding key derivation ---

    def _validate_moments_dtype(self):
        try:
            mdt = jnp.dtype(getattr(self, "moments_dtype", "float32"))
        except TypeError:
            mdt = None
        if mdt not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
            raise ValueError(
                f"moments_dtype must be float32 or bfloat16, got "
                f"{getattr(self, 'moments_dtype', None)!r}")

    @property
    def _moments_dtype(self):
        return jnp.dtype(getattr(self, "moments_dtype", "float32"))

    def _sr_key(self, step, seed):
        """Per-step SR key, or None when fp32 moments / SR disabled."""
        if (self._moments_dtype == jnp.dtype(jnp.bfloat16)
                and getattr(self, "stochastic_rounding", False)):
            return jax.random.fold_in(jax.random.PRNGKey(seed), step)
        return None

    def _finish_step(self, skip_if, new_params, new_state, params, state):
        """Apply the overflow step-skip select (params, moments, AND the
        step counter stay untouched on skip)."""
        if skip_if is None:
            return new_params, new_state
        out_p = tree_select(skip_if, params, new_params)
        out_s = tree_select(skip_if, state, new_state)
        return out_p, out_s

    def apply_gradients(self, grads, state, params, *, skip_if=None,
                        lr=None, grad_scale=None):
        """Uniform, donation-friendly apply surface for step builders.

        Every fused optimizer's ``step`` keeps its own signature quirks
        (FusedLAMB grows a ``grad_scale`` kwarg and then returns a
        3-tuple; the others don't take it). A donated fused train step
        needs ONE entry point whose return is always ``(params, state)``
        and whose output leaves are bit-compatible (same shape + dtype)
        with the inputs — XLA only aliases a donated input buffer into
        an output of identical layout, and silently falls back to a
        copy otherwise. This method normalizes the signature, folds a
        ``grad_scale`` unscale into the step when the optimizer supports
        it natively (or pre-unscales when it doesn't), and raises at
        trace time if an optimizer update would break buffer aliasing.
        """
        import inspect

        if grad_scale is not None:
            if "grad_scale" in inspect.signature(self.step).parameters:
                out = self.step(grads, state, params, skip_if=skip_if,
                                lr=lr, grad_scale=grad_scale)
                new_params, new_state = out[0], out[1]
            else:
                inv = 1.0 / jnp.asarray(grad_scale, jnp.float32)
                grads = jax.tree.map(
                    lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype),
                    grads)
                from apex_tpu.utils.pytree import all_finite
                found = jnp.logical_not(all_finite(grads))
                skip_if = (found if skip_if is None
                           else jnp.logical_or(skip_if, found))
                new_params, new_state = self.step(grads, state, params,
                                                  skip_if=skip_if, lr=lr)
        else:
            new_params, new_state = self.step(grads, state, params,
                                              skip_if=skip_if, lr=lr)
        self._check_alias_compatible(params, new_params, "params")
        self._check_alias_compatible(state, new_state, "state")
        return new_params, new_state

    @staticmethod
    def _check_alias_compatible(old, new, what: str):
        """Raise if ``new``'s leaves can't alias ``old``'s donated
        buffers (shape/dtype drift = XLA drops donation with only a
        warning; tests need a hard signal)."""
        old_l, new_l = jax.tree.leaves(old), jax.tree.leaves(new)
        if len(old_l) != len(new_l):
            raise ValueError(
                f"optimizer step changed the {what} tree arity "
                f"({len(old_l)} -> {len(new_l)} leaves); donated buffers "
                f"cannot alias")
        for a, b in zip(old_l, new_l):
            a_shape, b_shape = jnp.shape(a), jnp.shape(b)
            a_dt = jnp.asarray(a).dtype if not hasattr(a, "dtype") else a.dtype
            b_dt = jnp.asarray(b).dtype if not hasattr(b, "dtype") else b.dtype
            if a_shape != b_shape or a_dt != b_dt:
                raise ValueError(
                    f"optimizer step changed a {what} leaf from "
                    f"{a_dt}{list(a_shape)} to {b_dt}{list(b_shape)}; a "
                    f"donated buffer can only alias an identically-"
                    f"shaped, identically-typed output")

    def as_optax(self):
        """Adapt to an ``optax.GradientTransformation``.

        The transformation's update returns ``new_params - params`` so it
        composes with ``optax.apply_updates``. Requires params.
        """
        import optax

        opt = self

        def init_fn(params):
            return opt.init(params)

        def update_fn(grads, state, params=None):
            if params is None:
                raise ValueError(f"{type(opt).__name__}.as_optax() requires params")
            new_params, new_state = opt.step(grads, state, params)
            updates = jax.tree.map(
                lambda n, p: (n.astype(jnp.float32) - p.astype(jnp.float32)).astype(p.dtype),
                new_params,
                params,
            )
            return updates, new_state

        return optax.GradientTransformation(init_fn, update_fn)
