"""Shared machinery for the fused optimizers.

The reference optimizers (``apex/optimizers/*``, SURVEY.md §2.1) are
torch ``Optimizer`` subclasses whose ``step()`` makes one
``multi_tensor_applier`` call. The rebuild keeps that shape as a
functional core: each optimizer is an immutable config object with

- ``init(params) -> state``   (state is a pytree: step count + moments
  [+ fp32 master params when ``master_weights``])
- ``step(grads, state, params, skip_if=None, lr=None) -> (params, state)``

``skip_if`` is the amp overflow flag: when True the returned params/state
are the inputs unchanged and the step counter does not advance —
the in-graph equivalent of apex's patched ``optimizer.step()`` no-op on
overflow (SURVEY.md §3.2). ``as_optax()`` adapts any of these to an
``optax.GradientTransformation`` for idiomatic JAX training loops.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.utils.pytree import tree_cast, tree_select


def leaves_of(tree):
    return jax.tree.leaves(tree)


def like_tree(leaves, tree):
    return jax.tree.unflatten(jax.tree.structure(tree), leaves)


@dataclasses.dataclass(frozen=True)
class FusedOptimizer:
    """Base class: config dataclass + functional init/step."""

    lr: float = 1e-3
    weight_decay: float = 0.0
    master_weights: bool = False

    def with_master_weights(self, flag: bool = True):
        """Return a copy with fp32 master weights enabled (used by
        ``amp.initialize`` for O2, reference ``_process_optimizer``)."""
        return dataclasses.replace(self, master_weights=flag)

    def replace(self, **kw):
        return dataclasses.replace(self, **kw)

    # subclasses implement init() and step()

    def _master_init(self, params):
        if not self.master_weights:
            return None
        return tree_cast(params, jnp.float32)

    # --- shared bf16-moments machinery (round 5): subclasses exposing a
    # ``moments_dtype`` field share the validation, dtype resolution,
    # and per-step stochastic-rounding key derivation ---

    def _validate_moments_dtype(self):
        try:
            mdt = jnp.dtype(getattr(self, "moments_dtype", "float32"))
        except TypeError:
            mdt = None
        if mdt not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
            raise ValueError(
                f"moments_dtype must be float32 or bfloat16, got "
                f"{getattr(self, 'moments_dtype', None)!r}")

    @property
    def _moments_dtype(self):
        return jnp.dtype(getattr(self, "moments_dtype", "float32"))

    def _sr_key(self, step, seed):
        """Per-step SR key, or None when fp32 moments / SR disabled."""
        if (self._moments_dtype == jnp.dtype(jnp.bfloat16)
                and getattr(self, "stochastic_rounding", False)):
            return jax.random.fold_in(jax.random.PRNGKey(seed), step)
        return None

    def _finish_step(self, skip_if, new_params, new_state, params, state):
        """Apply the overflow step-skip select (params, moments, AND the
        step counter stay untouched on skip)."""
        if skip_if is None:
            return new_params, new_state
        out_p = tree_select(skip_if, params, new_params)
        out_s = tree_select(skip_if, state, new_state)
        return out_p, out_s

    def as_optax(self):
        """Adapt to an ``optax.GradientTransformation``.

        The transformation's update returns ``new_params - params`` so it
        composes with ``optax.apply_updates``. Requires params.
        """
        import optax

        opt = self

        def init_fn(params):
            return opt.init(params)

        def update_fn(grads, state, params=None):
            if params is None:
                raise ValueError(f"{type(opt).__name__}.as_optax() requires params")
            new_params, new_state = opt.step(grads, state, params)
            updates = jax.tree.map(
                lambda n, p: (n.astype(jnp.float32) - p.astype(jnp.float32)).astype(p.dtype),
                new_params,
                params,
            )
            return updates, new_state

        return optax.GradientTransformation(init_fn, update_fn)
