"""Out-of-process replicas: the parent-side handle and the
serialization layer (docs/fleet.md, "Process replicas").

:class:`ProcessReplica` runs one :class:`~apex_tpu.serving.engine.
InferenceEngine` in a CHILD OS PROCESS (``python -m apex_tpu.serving.
replica_worker``) and exposes the exact in-process replica surface —
``add_request`` / ``step`` / ``load`` / ``probe_prefix`` /
``export_requests`` / ``import_requests`` / ``pop_results`` /
``pop_stream_events`` / ``abort`` / ``checkpoint`` /
``export_prefix_payloads`` / ``import_prefix_payloads`` / ``stats`` —
as RPCs over the :mod:`~apex_tpu.serving.wire` frame protocol on the
child's stdio, so :class:`~apex_tpu.serving.fleet.FleetRouter` drives
process replicas and in-process engines through ONE code path and a
1-process-replica fleet certifies bit-identical to the in-process
fleet (tests/test_process_replica.py, ``bench_serving_process``).

The failure contract mirrors the in-process one deliberately:

- engine-level refusals come back as the REAL exception types
  (``QueueFullError``, ``TenantThrottledError``, ``ValueError``,
  ``IntegrityError`` with its site/detail) so the router's door
  logic, import-refusal handling, and zero-lost accounting apply
  unchanged;
- a torn or rotted RESPONSE frame (``IntegrityError`` from the wire)
  is retried by resending the SAME request id up to ``rpc_retries``
  times — the worker's at-most-once dedupe answers a duplicate id
  from its response cache WITHOUT re-executing, so a retried
  ``add_request`` can never double-enqueue;
- an unresponsive child (:class:`~apex_tpu.serving.wire.
  WireTimeoutError`), a closed pipe, or exhausted retries mark the
  handle DEAD and raise :class:`ReplicaUnavailableError` — which
  escapes the router's ``step()`` exactly like an in-process engine
  exception and drives the existing ``_fail_replica`` checkpoint
  failover. The parent caches every checkpoint the child piggybacks
  on its ``step()`` responses in :attr:`ProcessReplica.
  last_checkpoint`, so failover-from-checkpoint reads host-side
  state even when the child died mid-SIGKILL.

Terminal statuses: the in-process engine writes terminal status onto
the caller's own :class:`Request` object; a child can only mutate its
deserialized copy, so the handle mirrors the status onto the original
object when the verdict drains through ``pop_results`` (and
immediately for a door ``throttled``). Requests that migrate away via
``export_requests`` stop being mirrored — identical to the in-process
fleet, where an imported request is a fresh object too.

Everything here and in the worker speaks JSON-able records; numpy
payloads ride :func:`wire.encode_arrays`. The frame/RPC layer itself
is stdlib-only — jax/numpy appear only inside the engine-facing
serialization helpers.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from apex_tpu.serving import wire
from apex_tpu.serving.engine import (
    DEFAULT_TENANT,
    EngineConfig,
    QueueFullError,
    Request,
    RequestResult,
    TenantQuota,
    TenantThrottledError,
)
from apex_tpu.serving.sampling import SamplingParams
from apex_tpu.utils.faults import (
    FaultPlan,
    plan_record,
    split_plan,
    validate_wire_specs,
    wire_chaos,
)
from apex_tpu.utils.integrity import IntegrityError

# a child boots jax + compiles nothing until first step, but the
# import alone is tens of seconds on a cold cache — the handshake gets
# its own generous budget, separate from the per-RPC timeout
DEFAULT_BOOT_TIMEOUT_S = 300.0
DEFAULT_RPC_TIMEOUT_S = 300.0
DEFAULT_RPC_RETRIES = 2


class ReplicaUnavailableError(RuntimeError):
    """The child replica process is dead or unresponsive (closed pipe,
    RPC timeout, or frame retries exhausted). Escapes the router's
    ``step()`` like any in-process engine failure and drives the
    checkpoint-failover path."""


class RemoteEngineError(RuntimeError):
    """A child-side exception with no richer local mapping (the mapped
    types — queue/tenant sheds, ``ValueError``, ``IntegrityError`` —
    re-raise as themselves)."""


# -- serialization: configs, requests, models, clocks -----------------------


def engine_config_record(config: EngineConfig) -> Dict:
    """An :class:`EngineConfig` as a JSON-able record — every field,
    operational knobs included (the child must run the SAME engine,
    not just a fingerprint-equal one). ``kv_dtype`` flattens to its
    canonical dtype string, ``tenant_quotas`` to plain dicts."""
    import dataclasses

    import jax.numpy as jnp

    rec = {}
    for f in dataclasses.fields(EngineConfig):
        v = getattr(config, f.name)
        if f.name == "kv_dtype":
            v = None if v is None else str(jnp.dtype(v))
        elif f.name == "mesh_shape":
            v = None if v is None else [int(x) for x in v]
        elif f.name == "tenant_quotas" and v is not None:
            v = {t: {"max_waiting": q.max_waiting,
                     "max_resident_blocks": q.max_resident_blocks,
                     "tokens_per_s": q.tokens_per_s}
                 for t, q in v.items()}
        elif f.name == "tenant_weights" and v is not None:
            v = {t: float(w) for t, w in v.items()}
        rec[f.name] = v
    return rec


def engine_config_from_record(rec: Dict) -> EngineConfig:
    """Invert :func:`engine_config_record`. ``EngineConfig.
    __post_init__`` re-validates everything, so a rotted record fails
    loudly at construction. A dtype STRING stays a string — jax
    accepts it everywhere a dtype goes, and the config fingerprint
    canonicalizes through ``jnp.dtype`` anyway."""
    kw = dict(rec)
    if kw.get("mesh_shape") is not None:
        kw["mesh_shape"] = tuple(int(x) for x in kw["mesh_shape"])
    if kw.get("tenant_quotas") is not None:
        kw["tenant_quotas"] = {
            t: TenantQuota(max_waiting=q.get("max_waiting"),
                           max_resident_blocks=q.get("max_resident_blocks"),
                           tokens_per_s=q.get("tokens_per_s"))
            for t, q in kw["tenant_quotas"].items()}
    return EngineConfig(**kw)


def request_record(req: Request) -> Dict:
    """A :class:`Request` as the JSON-able shape ``add_request`` ships
    to the child (original ``deadline_s`` budget — the child's door
    anchors it, exactly as the in-process door would)."""
    return {
        "uid": req.uid,
        "prompt": [int(t) for t in req.prompt],
        "max_new_tokens": int(req.max_new_tokens),
        "eos_token_id": (None if req.eos_token_id is None
                         else int(req.eos_token_id)),
        "sampling": {"temperature": float(req.sampling.temperature),
                     "top_k": int(req.sampling.top_k),
                     "top_p": float(req.sampling.top_p)},
        "deadline_s": (None if req.deadline_s is None
                       else float(req.deadline_s)),
        "priority": int(req.priority),
        "tenant": str(req.tenant),
    }


def request_from_record(rec: Dict) -> Request:
    s = rec.get("sampling") or {}
    return Request(
        uid=rec["uid"], prompt=list(rec["prompt"]),
        max_new_tokens=int(rec["max_new_tokens"]),
        sampling=SamplingParams(
            temperature=float(s.get("temperature", 0.0)),
            top_k=int(s.get("top_k", 0)),
            top_p=float(s.get("top_p", 1.0))),
        eos_token_id=rec.get("eos_token_id"),
        deadline_s=rec.get("deadline_s"),
        priority=int(rec.get("priority", 0)),
        tenant=str(rec.get("tenant", DEFAULT_TENANT)))


def gpt_model_spec(cfg, init_seed: int = 0, init_len: int = 8) -> Dict:
    """A GPT model + its seeded init as a JSON-able spec: the child
    rebuilds the SAME weights from the same PRNG key, and the parent's
    ``params_checksum`` handshake proves it did (a spec drifting from
    the parent's params is refused at boot, not discovered as an SDC
    mystery later)."""
    import dataclasses

    import jax.numpy as jnp

    d = dataclasses.asdict(cfg)
    d["dtype"] = str(jnp.dtype(d["dtype"]))
    return {"family": "gpt", "config": d,
            "init_seed": int(init_seed), "init_len": int(init_len)}


def build_model_from_spec(spec: Dict):
    """``(model, params)`` from a :func:`gpt_model_spec` record — the
    child's half of the weight handshake (also usable parent-side to
    build the router's own copy from the same spec)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.models import GPTConfig, GPTLMHeadModel

    family = spec.get("family")
    if family != "gpt":
        raise ValueError(f"unknown model family {family!r} in model "
                         "spec (supported: 'gpt')")
    d = dict(spec["config"])
    d["dtype"] = jnp.dtype(d.get("dtype", "float32"))
    model = GPTLMHeadModel(GPTConfig(**d))
    params = model.init(
        jax.random.PRNGKey(int(spec.get("init_seed", 0))),
        jnp.zeros((1, int(spec.get("init_len", 8))), jnp.int32))
    return model, params


def params_checksum(params, weight_quantization: Optional[str] = None) -> str:
    """SHA-256 over every weight leaf (path-keyed, order-independent)
    via the house :func:`~apex_tpu.utils.integrity.payload_checksum` —
    the boot-time proof that parent and child hold bit-identical
    weights.

    ``weight_quantization`` makes the checksum cover the QUANTIZED
    representation the engine actually serves: the fp tree is
    re-expressed via :func:`~apex_tpu.models.gpt.quantize_gpt_params`
    (deterministic round-to-nearest, so equal fp weights always hash
    equal) and the mode itself is folded in as an extra leaf — a
    replica booted with a mismatched mode computes a different
    checksum from the same spec and is refused at hello, instead of
    serving different-numerics logits behind an "equal weights"
    handshake."""
    import jax
    import numpy as np

    from apex_tpu.utils.integrity import payload_checksum

    if weight_quantization is not None:
        from apex_tpu.models.gpt import quantize_gpt_params

        params = quantize_gpt_params(params, weight_quantization)
    leaves, _ = jax.tree_util.tree_flatten_with_path(params)
    payload = {jax.tree_util.keystr(path): np.asarray(leaf)
               for path, leaf in leaves}
    if weight_quantization is not None:
        payload["__weight_quantization__"] = np.frombuffer(
            weight_quantization.encode("utf-8"), np.uint8)
    return payload_checksum(payload)


def clock_from_spec(spec: Optional[Dict]):
    """A child-side clock from its JSON spec: ``None`` /
    ``{"kind": "monotonic"}`` → the engine's default wall clock;
    ``{"kind": "constant", "t": v}`` → the frozen clock the identity
    certs run both sides on (a parent lambda cannot cross a process
    boundary — the spec is the serializable subset that can)."""
    if spec is None:
        return None
    kind = spec.get("kind", "monotonic")
    if kind == "monotonic":
        return None
    if kind == "constant":
        t = float(spec["t"])
        return lambda: t
    raise ValueError(f"unknown clock spec kind {kind!r} "
                     "(supported: 'monotonic', 'constant')")


def _map_error(err: Dict) -> Exception:
    """A child-side exception record back into the REAL local type
    where the router's logic depends on it; everything unmapped
    becomes :class:`RemoteEngineError` (still carrying the child-side
    type name)."""
    etype = err.get("type")
    msg = str(err.get("message", ""))
    if etype == "QueueFullError":
        return QueueFullError(msg)
    if etype == "TenantThrottledError":
        return TenantThrottledError(msg)
    if etype == "ValueError":
        return ValueError(msg)
    if etype == "IntegrityError":
        return IntegrityError(str(err.get("site", "wire")),
                              str(err.get("detail", msg)))
    return RemoteEngineError(f"{etype}: {msg}")


class ProcessReplica:
    """One engine in a child OS process, behind the in-process replica
    surface. See the module docstring for the failure contract; see
    :mod:`~apex_tpu.serving.replica_worker` for the other end.

    ``faults`` takes the replica's WHOLE chaos plan: ``"wire"``-site
    rules stay on this (parent) side as the frame chaos hook
    (:func:`~apex_tpu.utils.faults.wire_chaos`), the rest ships to the
    child engine — one plan still describes one replica. ``on_retry``
    / ``on_timeout`` are the router's counter hooks (``stats()``'s
    ``num_rpc_retries`` / ``num_rpc_timeouts``).
    """

    mode = "process"

    def __init__(self, engine_config: EngineConfig, model_spec: Dict, *,
                 faults: Optional[FaultPlan] = None,
                 clock_spec: Optional[Dict] = None,
                 rpc_timeout_s: float = DEFAULT_RPC_TIMEOUT_S,
                 rpc_retries: int = DEFAULT_RPC_RETRIES,
                 boot_timeout_s: float = DEFAULT_BOOT_TIMEOUT_S,
                 expect_params_checksum: Optional[str] = None,
                 on_retry: Optional[Callable[[], None]] = None,
                 on_timeout: Optional[Callable[[], None]] = None):
        wire_plan, child_plan = split_plan(faults, "wire")
        if wire_plan is not None:
            validate_wire_specs(wire_plan.specs)
        self._chaos = None if wire_plan is None else wire_chaos(wire_plan)
        self.wire_faults = wire_plan  # audit surface for tests
        self._timeout_s = float(rpc_timeout_s)
        self._retries = int(rpc_retries)
        self._on_retry = on_retry
        self._on_timeout = on_timeout
        self._seq = 0
        self._dead = False
        self._live: Dict[str, Request] = {}
        self.last_checkpoint: Optional[Dict] = None
        self._proc = subprocess.Popen(
            [sys.executable, "-m", "apex_tpu.serving.replica_worker"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE)
        self.pid = self._proc.pid
        try:
            wire.write_frame(self._proc.stdin.fileno(), {
                "type": "init",
                "config": engine_config_record(engine_config),
                "model_spec": model_spec,
                "params_checksum": expect_params_checksum,
                "faults": (None if child_plan is None
                           else plan_record(child_plan)),
                "clock": clock_spec,
            })
            # the hello frame is read WITHOUT the chaos hook: boot is
            # not an RPC, and a plan aimed at call 0 should hit the
            # first real call on both chaos and chaos-free runs
            hello = wire.read_frame(self._proc.stdout.fileno(),
                                    timeout_s=float(boot_timeout_s))
        except Exception:
            self._abandon()
            raise
        if not hello.get("ok"):
            err = _map_error(hello.get("error") or {})
            self._abandon()
            raise err
        self.child_pid = int(hello.get("pid", self.pid))

    # -- the RPC core ------------------------------------------------------

    def _unavailable(self, why: str) -> ReplicaUnavailableError:
        self._abandon()
        return ReplicaUnavailableError(
            f"replica child pid {self.pid} unavailable: {why}")

    def _call(self, method: str, *args):
        if self._dead:
            raise ReplicaUnavailableError(
                f"replica child pid {self.pid} is already dead")
        self._seq += 1
        rid = self._seq
        frame = {"type": "call", "id": rid, "method": method,
                 "args": list(args)}
        attempts = 0
        while True:
            try:
                wire.write_frame(self._proc.stdin.fileno(), frame)
                resp = wire.read_frame(self._proc.stdout.fileno(),
                                       timeout_s=self._timeout_s,
                                       chaos=self._chaos)
            except IntegrityError as e:
                # a torn/rotted frame MAY be transient — resend the
                # same id; the worker's dedupe makes the retry safe
                attempts += 1
                if attempts > self._retries:
                    raise self._unavailable(
                        f"{method} failed {attempts} frame attempts; "
                        f"last: {e}")
                if self._on_retry is not None:
                    self._on_retry()
                continue
            except wire.WireTimeoutError as e:
                if self._on_timeout is not None:
                    self._on_timeout()
                raise self._unavailable(f"{method} timed out: {e}")
            except (wire.WireClosedError, BrokenPipeError, OSError) as e:
                raise self._unavailable(
                    f"pipe closed during {method}: "
                    f"{type(e).__name__}: {e}")
            if resp.get("id") != rid:
                # the child reported a torn REQUEST (id None) — resend
                attempts += 1
                if attempts > self._retries:
                    raise self._unavailable(
                        f"{method} failed {attempts} frame attempts; "
                        f"child saw a torn request")
                if self._on_retry is not None:
                    self._on_retry()
                continue
            if "checkpoint" in resp:
                self.last_checkpoint = resp["checkpoint"]
            if resp.get("ok"):
                return resp.get("result")
            raise _map_error(resp.get("error") or {})

    # -- the replica surface ----------------------------------------------

    def add_request(self, request: Request) -> int:
        try:
            arrival = self._call("add_request", request_record(request))
        except TenantThrottledError:
            # mirror the in-process door: a quota shed leaves terminal
            # status "throttled" on the caller's object (the result
            # record itself drains from the child via pop_results)
            object.__setattr__(request, "status", "throttled")
            raise
        except QueueFullError:
            object.__setattr__(request, "status", None)
            raise
        object.__setattr__(request, "status", None)
        self._live[request.uid] = request
        return int(arrival)

    def step(self) -> bool:
        return bool(self._call("step"))

    @property
    def has_work(self) -> bool:
        return bool(self._call("has_work"))

    def load(self) -> Dict[str, float]:
        return {k: float(v) for k, v in self._call("load").items()}

    def probe_prefix(self, hashes: Sequence[str]) -> int:
        return int(self._call("probe_prefix", list(hashes)))

    def spilled_hashes(self) -> Dict[str, str]:
        return {str(h): str(t)
                for h, t in self._call("spilled_hashes").items()}

    def decoding_uids(self) -> List[str]:
        return [str(u) for u in self._call("decoding_uids")]

    def exported_arrival(self, uid: str) -> Optional[int]:
        v = self._call("exported_arrival", str(uid))
        return None if v is None else int(v)

    def drop_stream_events(self, uid: str) -> int:
        return int(self._call("drop_stream_events", str(uid)))

    def export_requests(self, uids: Optional[Sequence[str]] = None
                        ) -> List[Dict]:
        records = self._call(
            "export_requests", None if uids is None else list(uids))
        for rec in records:
            # migrated away: the destination owns a fresh object now,
            # exactly as in the in-process fleet
            self._live.pop(rec.get("uid"), None)
        return records

    def import_requests(self, records: Sequence[Dict]) -> int:
        return int(self._call("import_requests", list(records)))

    def pop_results(self) -> Dict[str, RequestResult]:
        out = {}
        for uid, rec in self._call("pop_results").items():
            res = RequestResult(tokens=[int(t) for t in rec["tokens"]],
                                status=rec["status"])
            req = self._live.pop(uid, None)
            if req is not None:
                object.__setattr__(req, "status", res.status)
            out[uid] = res
        return out

    def pop_stream_events(self) -> List[Tuple[str, int, bool]]:
        return [(u, int(t), bool(last))
                for u, t, last in self._call("pop_stream_events")]

    def abort(self, uid: str) -> bool:
        return bool(self._call("abort", uid))

    def checkpoint(self) -> Dict:
        snap = self._call("checkpoint")
        self.last_checkpoint = snap
        return snap

    def export_prefix_payloads(self, hashes: Sequence[str]) -> Dict:
        return wire.decode_arrays(
            self._call("export_prefix_payloads", list(hashes)))

    def import_prefix_payloads(self, payloads: Dict) -> int:
        return int(self._call("import_prefix_payloads",
                              wire.encode_arrays(payloads)))

    def stats(self) -> Dict:
        return self._call("stats")

    # -- the narrow router accessors ---------------------------------------

    @property
    def block_weight(self) -> float:
        return float(self._call("block_weight"))

    @property
    def queue_depth(self) -> int:
        return int(self._call("queue_depth"))

    @property
    def active_slot_count(self) -> int:
        return int(self._call("active_slot_count"))

    def tenant_charge(self, tenant: str):
        return self._call("tenant_charge", tenant)

    def tenant_depth(self, tenant: str) -> int:
        return int(self._call("tenant_depth", tenant))

    # -- lifecycle ---------------------------------------------------------

    @property
    def alive(self) -> bool:
        """True while the handle is usable AND the child has not been
        reaped (a SIGKILLed child flips this on the next poll)."""
        return not self._dead and self._proc.poll() is None

    def _abandon(self) -> None:
        """Mark dead and reap, keeping whatever ``last_checkpoint``
        was already cached — the failover picture survives the
        corpse."""
        self._dead = True
        try:
            if self._proc.poll() is None:
                self._proc.kill()
            self._proc.wait(timeout=10)
        except Exception:
            pass
        for pipe in (self._proc.stdin, self._proc.stdout):
            try:
                if pipe is not None:
                    pipe.close()
            except Exception:
                pass

    def kill(self) -> None:
        """SIGKILL the child — the REAL chaos hook behind the router's
        ``kill_replica`` in process mode (and the disposal path for a
        corpse). Idempotent."""
        if not self._dead and self._proc.poll() is None:
            try:
                os.kill(self.pid, signal.SIGKILL)
            except OSError:
                pass
        self._abandon()

    def close(self) -> None:
        """Graceful shutdown: ask the worker to exit, then reap. Falls
        back to :meth:`kill` when the child is already unreachable."""
        if self._dead:
            return
        try:
            self._seq += 1
            wire.write_frame(self._proc.stdin.fileno(),
                             {"type": "shutdown", "id": self._seq})
            wire.read_frame(self._proc.stdout.fileno(), timeout_s=10.0)
        except Exception:
            pass
        self._abandon()
