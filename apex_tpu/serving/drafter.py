"""Draft-token proposers for speculative decoding (docs/serving.md).

Speculative decoding splits token generation into a cheap **proposer**
and the engine's one-dispatch **verifier**: a drafter guesses up to
``spec_tokens`` continuation tokens per lane, the target model scores
every candidate position in a single forward, and the rejection rule in
:func:`apex_tpu.serving.sampling.spec_verify_tokens` accepts a prefix of
the guesses without changing the output distribution (bit-identically,
for greedy). The drafter therefore has exactly one obligation beyond
the ``propose`` signature: it must be a **pure function of the token
history** — so a run is reproducible, and so the greedy certification
(speculative output bit-identical to non-speculative greedy across
lane placements and preemption/resume) holds; sampled lanes stay
exactly distribution-preserving, though their realized draws depend on
where span boundaries fall (docs/serving.md). Proposal *quality* only
affects throughput, never correctness: every rejected token is
corrected from the target distribution.

Two drafters ship behind the interface:

- :class:`NgramDrafter` — prompt-lookup / n-gram matching (the
  "assisted generation" trick): find the longest recent-suffix n-gram
  that occurred earlier in the history and propose the tokens that
  followed it. Zero model cost, zero device work, and very effective on
  the traffic speculative decoding targets — templated output,
  multi-turn echoes, code, and the repetition attractors greedy
  decoding falls into.
- :class:`GPTDrafter` — a small GPT (same
  :class:`~apex_tpu.models.gpt.GPTLMHeadModel` contract) greedy-decoding
  the continuation over a fixed context window. One jitted program at
  one fixed shape, so the drafter cannot erode the engine's pinned
  compile counts; it runs its window forward once per proposed token
  (no KV cache of its own — the drafter is meant to be small enough
  that this is still cheap next to one target-model decode step).

A drafter that raises is **quarantined**, not fatal: the engine wraps
``propose`` in the shared retry policy
(:func:`apex_tpu.utils.faults.guarded_call`) and permanently degrades
to non-speculative decoding when retries exhaust — the verify program
with zero proposals is exactly a single decode step, so a drafterless
speculative engine keeps emitting bit-identical tokens.
"""

from __future__ import annotations

from typing import List, Sequence


class Drafter:
    """The proposer interface: ``propose(history, max_tokens)`` returns
    up to ``max_tokens`` candidate continuation tokens for a sequence
    whose full visible token history (prompt + everything generated) is
    ``history``. Fewer — including zero — proposals are always legal;
    the engine verifies whatever it gets and falls back to an ordinary
    single-token step for lanes with no proposals. Implementations must
    be deterministic in ``history`` (see the module docstring)."""

    def propose(self, history: Sequence[int],
                max_tokens: int) -> List[int]:
        raise NotImplementedError


class NgramDrafter(Drafter):
    """Prompt-lookup drafting: propose the continuation of the most
    recent earlier occurrence of the history's suffix n-gram.

    For ``n`` from ``max_ngram`` down to ``min_ngram``, the drafter
    looks for the latest earlier position where the history's final
    ``n`` tokens already appeared; on a match it proposes the tokens
    that followed that occurrence, in order. Matching longest-suffix
    first keeps proposals conservative (a longer context match is a
    stronger signal); searching latest-first prefers the freshest
    continuation when a pattern occurs more than once. A continuation
    that runs into the present **extends periodically** (the proposals
    feed themselves): a greedy decode circling a repetition attractor
    matches its suffix one period back, where the raw continuation is
    at most one period long — wrapping turns that into a full
    ``max_tokens`` proposal, and the verify chunk's shape is fixed at
    ``spec_tokens + 1`` either way, so the extra guesses ride the
    dispatch for free and a wrong tail merely gets rejected. No match
    — or a history shorter than ``min_ngram + 1`` — proposes nothing,
    which costs one ordinary decode step.

    Pure Python over host token lists: O(len(history) * max_ngram) per
    call, negligible next to a model dispatch at serving context
    lengths (the engine calls it once per decoding lane per decode
    dispatch, i.e. once per speculative span, not per token).
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"min_ngram={min_ngram}, max_ngram={max_ngram}")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def propose(self, history: Sequence[int],
                max_tokens: int) -> List[int]:
        toks = list(history)
        L = len(toks)
        if max_tokens < 1:
            return []
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            suffix = toks[L - n:]
            # latest EARLIER occurrence: start positions where the
            # match's continuation is not the suffix itself
            for s in range(L - n - 1, -1, -1):
                if toks[s:s + n] == suffix:
                    out: List[int] = []
                    pos = s + n
                    while len(out) < max_tokens:
                        # past the present, the continuation is the
                        # proposal itself: periodic extension
                        out.append(toks[pos] if pos < L
                                   else out[pos - L])
                        pos += 1
                    return out
        return []


class GPTDrafter(Drafter):
    """A small-GPT proposer: greedy-decode ``max_tokens`` continuation
    tokens with a (cheaper) draft model over the last ``window`` tokens
    of the history.

    The draft model follows the same ``GPTLMHeadModel`` apply contract
    as the target, with its own params — typically far fewer layers /
    a narrower width. The forward runs at ONE fixed ``[1, window]``
    shape (right-padded, logits read at the last real position — causal
    attention makes the padding invisible), so the drafter owns exactly
    one compiled program for the engine's lifetime. Each proposed token
    is one window forward; there is deliberately no drafter-side KV
    cache — the drafter must be small enough that recompute is cheap,
    and keeping it stateless preserves the pure-function-of-history
    contract preemption/resume determinism requires.
    """

    def __init__(self, model, params, window: int = 32):
        import jax
        import jax.numpy as jnp

        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if window > model.cfg.max_position_embeddings:
            raise ValueError(
                f"window ({window}) exceeds the draft model's "
                f"max_position_embeddings "
                f"({model.cfg.max_position_embeddings})")
        self.model = model
        self.params = params
        self.window = int(window)

        def _next_token(params, ids, last_idx):
            logits = self.model.apply(params, ids, deterministic=True)
            return jnp.argmax(
                logits[0, last_idx].astype(jnp.float32)).astype(jnp.int32)

        self._next = jax.jit(_next_token)

    def propose(self, history: Sequence[int],
                max_tokens: int) -> List[int]:
        import jax.numpy as jnp
        import numpy as np

        toks = [int(t) for t in history]
        out: List[int] = []
        for _ in range(max(int(max_tokens), 0)):
            w = toks[-self.window:]
            ids = np.zeros((1, self.window), np.int32)
            ids[0, : len(w)] = w
            nxt = int(self._next(self.params, jnp.asarray(ids),
                                 jnp.int32(len(w) - 1)))
            out.append(nxt)
            toks.append(nxt)
        return out
