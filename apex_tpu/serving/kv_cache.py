"""Paged KV-cache: fixed-shape block pools + host-side block accounting.

The serving-side analog of vLLM's PagedAttention cache (PAPERS.md) on
XLA's terms: device memory is a fixed pool of ``num_blocks`` blocks per
layer, laid out ``[num_layers, num_blocks, block_size, num_heads,
head_dim]``, and a sequence owns a *block table* — the ordered list of
block ids holding its tokens. Every jitted program sees only fixed
shapes (the pool, a ``[B, max_blocks_per_seq]`` int32 table, and
``[B]`` lengths), so admission, eviction, and sequence growth never
trigger recompilation: the continuous-batching engine swaps table
*values*, not shapes.

Division of labor (the load-bearing design point):

- **Device side** (jit-stable, pure): :func:`paged_write` scatters new
  K/V into blocks, :func:`gather_kv` reads a sequence back out,
  :func:`copy_block` duplicates one block (the copy-on-write step), and
  :func:`gather_blocks` applies a defrag permutation. All take the
  pool + int32 indices; invalid slots are routed to an out-of-bounds
  block id and dropped by the scatter (``mode="drop"``), so inactive
  batch slots cost nothing and write nowhere.
- **Host side** (Python, between steps): :class:`BlockAllocator` owns
  the block ids — a free list, a per-block **reference count** (blocks
  are shared between sequences under prefix caching), and a
  **prefix index** mapping a hash-chain of full-block token contents to
  the block id that already holds those tokens. ``free`` releases a
  reference; a registered block whose refcount hits zero is *retained*
  in an LRU set and only actually evicted when the free list runs dry
  (:meth:`BlockAllocator.alloc` evicts least-recently-used cached
  blocks on demand). The scheduler consults the allocator; the device
  never sees it.

Prefix caching hashes full blocks only: ``hash_block_tokens`` chains
each block's hash through its predecessor's, so a block id is matched
only when the *entire* token prefix up to and including that block is
identical — the RadixAttention sharing rule (PAPERS.md) collapsed onto
a flat dict.

Storage dtype rides the existing amp policy: :func:`default_kv_dtype`
returns the active ``amp.initialize`` handle's compute dtype (bf16 for
O1-O3, fp32 for O0) unless overridden — the cache is activation-class
state, so it follows the activation precision, not the master-weight
precision.

**Quantized block storage** (``KVCache.create(quantization="int8")``,
docs/serving.md memory tiers): the K/V payload pools store int8 (or
fp8 where the backend supports it) with fp32 scales carried alongside
the pool, organized per block — ``k_scale``/``v_scale`` are ``[L, N,
bs, H]``, one scale per written (token, head) row, scattered/copied/
permuted with exactly the block ops that move the payload (so CoW,
defrag, and spill move a block's scales with its bytes). The quantize
path reuses :func:`apex_tpu.ops.multi_tensor.stochastic_round` keyed
by the token's ABSOLUTE cache position, so a given K/V row always
rounds the same way regardless of lane placement, ``decode_steps``,
or preemption/resume — quantized runs keep the engine's determinism
contract. Dequantization happens inside the attention read
(:func:`apex_tpu.ops.flash_attention.paged_prefill_attention`). With
``quantization=None`` the scale fields are ``None`` and every code
path is the pre-quantization one, bit for bit.

**Host-RAM spill tier** (:class:`HostSpillStore`, docs/serving.md):
instead of discarding an LRU-evicted or ladder-flushed prefix block,
the allocator (when a store is attached) copies its contents to a
bounded host-side LRU keyed by the block's SHA-256 chain hash; a later
prefix match re-admits it by device upload instead of recompute. The
store holds only blocks NOT currently device-indexed (re-admission
pops; re-registration discards) — the invariant
:meth:`BlockAllocator.check_integrity` enforces.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from apex_tpu.utils.integrity import payload_checksum

# the tenant every un-labelled caller is accounted to — single-tenant
# traffic runs entirely under this id and behaves exactly like the
# pre-tenancy allocator (the accounting is bookkeeping, never policy:
# allocation ORDER is tenant-blind, so default-tenant behavior is
# bit-identical)
DEFAULT_TENANT = "default"


def default_kv_dtype(dtype=None):
    """Resolve the KV-storage dtype through the amp policy: an explicit
    ``dtype`` wins; otherwise the last ``amp.initialize`` handle's
    compute dtype (bf16 under O1-O3); fp32 when amp was never set up."""
    if dtype is not None:
        return jnp.dtype(dtype)
    from apex_tpu.amp import _amp_state

    handle = _amp_state._amp_state.handle
    if handle is not None:
        return jnp.dtype(handle.properties.compute_dtype)
    return jnp.dtype(jnp.float32)


# the storage modes KVCache.create accepts (docs/serving.md memory
# tiers): None = full-precision (the amp-policy dtype), "int8" =
# symmetric int8 with per-row fp32 scales, "fp8" = float8_e4m3 with
# per-row fp32 scales (backends without an fp8 dtype raise at create)
KV_QUANT_MODES = (None, "int8", "fp8")

# base key of the quantizer's stochastic rounding, folded with each
# token's ABSOLUTE cache position — a module constant (not the engine
# seed) so the same K/V values at the same position always round
# identically across engines, restores, and re-prefills (the resume-
# determinism contract extended to the quantized path)
_KV_QUANT_SEED = 0x51CA17


def fp8_kv_dtype():
    """The fp8 storage dtype, or None when this jax has no fp8."""
    return getattr(jnp, "float8_e4m3fn", None)


def _quant_storage_dtype(quantization):
    if quantization == "int8":
        return jnp.dtype(jnp.int8)
    if quantization == "fp8":
        dt = fp8_kv_dtype()
        if dt is None:
            raise NotImplementedError(
                "kv quantization 'fp8' requires a jax with "
                "jnp.float8_e4m3fn; use 'int8' on this backend")
        return jnp.dtype(dt)
    raise ValueError(
        f"unknown kv quantization {quantization!r} "
        f"(expected one of {KV_QUANT_MODES})")


def _quant_value_max(quantization) -> float:
    """The quantizer's design max: scales are ``amax / qmax`` so the
    largest row magnitude maps onto the representable extreme."""
    if quantization == "int8":
        return 127.0
    return float(jnp.finfo(fp8_kv_dtype()).max)


def kv_block_bytes(num_layers: int, block_size: int, num_heads: int,
                   head_dim: int, dtype=None, quantization=None) -> int:
    """Device bytes one block costs across every layer — K + V payload
    plus (when quantized) the per-row fp32 scales. The number behind
    the bench's byte-budget pool sizing and the tenant ledger's
    reduced-footprint charge for quantized blocks."""
    if quantization is None:
        item = default_kv_dtype(dtype).itemsize
        return 2 * num_layers * block_size * num_heads * head_dim * item
    item = _quant_storage_dtype(quantization).itemsize
    payload = 2 * num_layers * block_size * num_heads * head_dim * item
    scales = 2 * num_layers * block_size * num_heads * 4
    return payload + scales


class KVCache(NamedTuple):
    """The device-side block pools (a pytree of two payload arrays,
    plus two scale arrays when quantized).

    ``k`` / ``v``: ``[num_layers, num_blocks, block_size, num_heads,
    head_dim]``. The pool is allocated once at engine start and updated
    functionally (scatter in, new pytree out); the layout keeps the
    ``(num_heads * head_dim)`` product in the trailing dims so a block
    row is lane-tileable on TPU.

    ``k_scale`` / ``v_scale`` (quantized storage only, else ``None``):
    ``[num_layers, num_blocks, block_size, num_heads]`` fp32 — one
    dequantization scale per written (token, head) row, organized per
    block so every op that moves a block (scatter, CoW copy, defrag
    permutation, host spill) moves its scales by the same indices.
    """

    k: jax.Array
    v: jax.Array
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @property
    def quantization(self) -> Optional[str]:
        """The storage mode this pool was created with (from dtype)."""
        if self.k_scale is None:
            return None
        return "int8" if self.k.dtype == jnp.int8 else "fp8"

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def block_size(self) -> int:
        return self.k.shape[2]

    @property
    def num_heads(self) -> int:
        return self.k.shape[3]

    @property
    def head_dim(self) -> int:
        return self.k.shape[4]

    def partition_specs(self, model_axis: str = "model",
                        batch_axis: Optional[str] = None) -> "KVCache":
        """The pool's mesh layout (docs/serving.md, "Mesh sharding"):
        a :class:`~jax.sharding.PartitionSpec` per pool, sharding the
        HEAD axis over ``model_axis`` — heads are the one axis the
        paged ops never index by data (scatter/gather/CoW/defrag all
        address layer/block/slot), so a head split needs zero
        collectives for pool maintenance, and the per-row scale pools
        split on the same axis so a block's scales stay colocated with
        its bytes. With ``batch_axis`` set (the data-parallel lane
        split), the BLOCK axis shards over it too: the allocator keeps
        a lane's blocks inside its shard's contiguous id range, so the
        sharded programs index only shard-local blocks and the split
        stays collective-free (docs/serving.md, "The batch axis").
        Returned as a KVCache-of-specs so callers ``tree.map`` it
        against the pool (``None`` scale fields line up with ``None``
        specs)."""
        payload = PartitionSpec(None, batch_axis, None, model_axis, None)
        scale = (None if self.k_scale is None
                 else PartitionSpec(None, batch_axis, None, model_axis))
        return KVCache(k=payload, v=payload, k_scale=scale, v_scale=scale)

    @classmethod
    def create(cls, num_layers: int, num_blocks: int, block_size: int,
               num_heads: int, head_dim: int, dtype=None,
               quantization: Optional[str] = None) -> "KVCache":
        shape = (num_layers, num_blocks, block_size, num_heads, head_dim)
        if quantization is None:
            dt = default_kv_dtype(dtype)
            return cls(k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt))
        dt = _quant_storage_dtype(quantization)
        sshape = shape[:-1]
        return cls(k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt),
                   k_scale=jnp.zeros(sshape, jnp.float32),
                   v_scale=jnp.zeros(sshape, jnp.float32))


class CacheOutOfBlocks(RuntimeError):
    """The allocator cannot serve an allocation even after evicting
    every refcount-0 cached block (admission should have been
    throttled, or the pool is simply undersized for the request)."""


def hash_block_tokens(prev_hash: Optional[str],
                      tokens: Sequence[int]) -> str:
    """Chain hash for one FULL block of token ids. ``prev_hash`` is the
    previous block's chain hash (``None`` for the first block), so equal
    hashes imply the whole prefix up to and including this block is
    equal — the property prefix matching relies on. SHA-256, not
    Python's builtin ``hash``: the index serves KV blocks on hash
    equality ALONE, so a collision would silently attend one request
    against another request's cache (wrong tokens + cross-request
    prompt leakage) — a non-cryptographic, PYTHONHASHSEED-dependent
    hash is not acceptable there (vLLM hit exactly this)."""
    h = hashlib.sha256()
    if prev_hash is not None:
        h.update(prev_hash.encode("ascii"))
    h.update(np.asarray(tokens, np.int64).tobytes())
    return h.hexdigest()


class BlockAllocator:
    """Host-side block-id accounting: free list + reference counts +
    the prefix-cache index.

    Lives entirely outside jit: the scheduler calls ``alloc`` / ``free``
    / ``match_prefix`` between steps and writes the resulting ids into
    host block tables, which are shipped to the device as plain int32
    inputs.

    Lifecycle of a block id:

    - **free** — on the free list; ``alloc`` hands it out with
      refcount 1.
    - **active** — refcount >= 1. ``acquire`` adds a reference (prefix
      sharing), ``free`` drops one; dropping below zero raises (the
      double-free guard).
    - **cached** — refcount 0 but registered in the prefix index: the
      block's contents are retained and matchable. ``alloc`` evicts
      cached blocks least-recently-used when the free list is empty;
      ``match_prefix`` revives them.
    """

    def __init__(self, num_blocks: int, block_weight: float = 1.0,
                 num_shards: int = 1):
        self.num_blocks = int(num_blocks)
        # the data-parallel block-shard count (the mesh's ``batch``
        # axis size): shard ``s`` owns the contiguous id range
        # ``[s * blocks_per_shard, (s + 1) * blocks_per_shard)``, and
        # shard-scoped alloc/evict/match keep every sequence's blocks
        # inside its lane's shard — the host-side invariant that makes
        # the device-side batch split collective-free. ``num_shards=1``
        # (the default and every pre-batch-axis engine) makes every
        # shard argument a no-op: behavior is bit-identical.
        self.num_shards = int(num_shards)
        if self.num_shards < 1:
            raise ValueError(
                f"num_shards must be >= 1, got {num_shards}")
        if self.num_blocks % self.num_shards:
            raise ValueError(
                f"num_shards ({self.num_shards}) must divide num_blocks "
                f"({self.num_blocks}): the pool splits into equal "
                "contiguous shard ranges")
        self.blocks_per_shard = self.num_blocks // self.num_shards
        # the per-block charge unit of the tenant ledger: quantized
        # pools pass their reduced byte footprint relative to the
        # full-precision block (e.g. ~0.28 for int8-vs-fp32), so a
        # tenant's fractional resident charge — and therefore its
        # max_resident_blocks quota — is denominated in FULL-PRECISION
        # block equivalents and quantization genuinely buys headroom.
        # 1.0 (the default, and every unquantized engine) keeps the
        # ledger bit-identical to the pre-quantization allocator.
        if not block_weight > 0:
            raise ValueError(
                f"block_weight must be > 0, got {block_weight}")
        self.block_weight = float(block_weight)
        # the host-RAM spill tier (attach_spill): evicted/flushed
        # prefix blocks copy to this store instead of vanishing
        self.spill_store: Optional["HostSpillStore"] = None
        self._spill_fetch = None
        # pop() from the end serves ascending ids first — keeps early
        # allocations compact, which makes defrag cheap in the common case
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self._ref: Dict[int, int] = {}            # block id -> refcount (>0)
        self._hash_to_block: Dict[str, int] = {}  # prefix index
        self._block_to_hash: Dict[int, str] = {}
        # refcount-0 registered blocks, insertion order = LRU order
        self._evictable: "OrderedDict[int, None]" = OrderedDict()
        self.num_evictions = 0
        # -- per-tenant accounting (docs/robustness.md, isolation) -----
        # Every reference is attributed to a tenant: _tenant_refs[b]
        # splits _ref[b] by holder, so a block shared across tenants
        # charges each FRACTIONALLY by refcount (tenant_charge). Cached
        # (refcount-0, prefix-indexed) blocks are attributed to the
        # tenant that REGISTERED them (_cached_owner), so rung-2
        # flushes and LRU evictions charge the tenant whose traffic
        # parked the block. Pure bookkeeping: allocation/eviction ORDER
        # never consults a tenant, so single-tenant behavior is
        # bit-identical to the pre-tenancy allocator.
        self._tenant_refs: Dict[int, Dict[str, int]] = {}
        self._cached_owner: Dict[int, str] = {}
        self._evicted_by_tenant: Dict[str, int] = {}
        self._flushed_by_tenant: Dict[str, int] = {}
        # incrementally-maintained fractional charge per tenant (the
        # O(1) read behind tenant_charge — the engine consults it per
        # admission candidate and per lane-growth check, so a scan
        # over every active block would sit on the scheduler's hot
        # path). _charge_block applies/removes one block's current
        # shares around each mutation; check_integrity re-derives the
        # exact sums and REBASES, bounding float drift.
        self._tenant_charge_acc: Dict[str, float] = {}

    # -- accounting --------------------------------------------------------

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_cached(self) -> int:
        """Refcount-0 blocks retained for prefix reuse (evictable)."""
        return len(self._evictable)

    @property
    def num_used(self) -> int:
        """Blocks currently referenced by live sequences."""
        return self.num_blocks - len(self._free) - len(self._evictable)

    def shard_of(self, block_id: int) -> int:
        """The data-parallel shard owning a block id (shard ranges are
        contiguous: ``id // blocks_per_shard``). Always 0 unsharded."""
        return int(block_id) // self.blocks_per_shard

    def free_in_shard(self, shard: int) -> int:
        """Free blocks inside one shard's id range."""
        return sum(1 for b in self._free
                   if b // self.blocks_per_shard == shard)

    def cached_in_shard(self, shard: int) -> int:
        """Evictable (refcount-0, prefix-indexed) blocks inside one
        shard's id range."""
        return sum(1 for b in self._evictable
                   if b // self.blocks_per_shard == shard)

    @property
    def utilization(self) -> float:
        """Fraction of pool blocks currently owned by live sequences."""
        return self.num_used / max(self.num_blocks, 1)

    def refcount(self, block_id: int) -> int:
        return self._ref.get(int(block_id), 0)

    def tenant_refcount(self, block_id: int, tenant: str) -> int:
        """How many of a block's references ``tenant`` holds."""
        return self._tenant_refs.get(int(block_id), {}).get(tenant, 0)

    def _charge_block(self, b: int, sign: int) -> None:
        """Apply (+1) or remove (-1) block ``b``'s CURRENT per-tenant
        fractional shares to the running charge accumulator — called
        around every mutation of the block's holder set."""
        total = self._ref.get(b, 0)
        if not total:
            return
        w = self.block_weight
        for t, n in self._tenant_refs[b].items():
            self._tenant_charge_acc[t] = \
                self._tenant_charge_acc.get(t, 0.0) + sign * w * n / total

    def tenant_charge(self, tenant: str) -> float:
        """The tenant's fractional resident-block charge: each active
        block contributes ``block_weight * tenant_refs / total_refs``
        — a private block charges ``block_weight`` (1.0 unquantized;
        the reduced byte footprint for quantized pools), a block
        shared evenly across two tenants charges each half that. This
        is the number the engine's ``max_resident_blocks`` quota is
        enforced against (sharing a prefix makes a tenant CHEAPER,
        never more expensive — and so does quantization). O(1):
        maintained incrementally by the mutation paths."""
        return max(0.0, self._tenant_charge_acc.get(tenant, 0.0))

    def tenant_stats(self) -> Dict[str, Dict[str, object]]:
        """Per-tenant accounting picture: fractional resident charge,
        cached (evictable) blocks attributed by registering tenant, and
        the eviction/flush attribution counters."""
        tenants = set(self._evicted_by_tenant) | set(self._flushed_by_tenant)
        for refs in self._tenant_refs.values():
            tenants.update(refs)
        cached_by: Dict[str, int] = {}
        for b in self._evictable:
            owner = self._cached_owner.get(b)
            if owner is not None:
                tenants.add(owner)
                cached_by[owner] = cached_by.get(owner, 0) + 1
        return {t: {
            "resident_block_charge": round(self.tenant_charge(t), 6),
            "cached_blocks": cached_by.get(t, 0),
            "evicted_blocks": self._evicted_by_tenant.get(t, 0),
            "flushed_blocks": self._flushed_by_tenant.get(t, 0),
        } for t in sorted(tenants)}

    # -- the host-RAM spill tier (docs/serving.md memory tiers) ------------

    def attach_spill(self, store: "HostSpillStore", fetch) -> None:
        """Wire the host spill tier in: every block
        :meth:`_evict_one` drops (LRU pressure or a ladder flush) is
        first copied to ``store`` under its chain hash, using
        ``fetch(block_id) -> payload dict | None`` to read the device
        contents (the engine owns the pool, so it owns the fetch — a
        fetch returning None, e.g. on a transient device error, simply
        skips the spill: the tier is an optimization, never a
        correctness dependency). :meth:`register_prefix` discards the
        stored copy for a hash the moment a device block is indexed
        under it, keeping the store's contents disjoint from the
        device index (the :meth:`check_integrity` invariant)."""
        self.spill_store = store
        self._spill_fetch = fetch

    # -- alloc / free / share ----------------------------------------------

    def _evict_one(self, flushed: bool = False,
                   shard: Optional[int] = None) -> int:
        """Drop the least-recently-used cached block (unregister it),
        charging the eviction to the tenant that registered the block
        (``flushed`` routes the charge to the flush counter — the
        degradation ladder's rung-2 accounting). With a spill tier
        attached, the block's contents are copied to the host store
        first — the eviction stops being a future recompute and
        becomes a future upload. ``shard`` restricts the LRU walk to
        one shard's id range (the batch-axis pools evict only where
        the allocation must land); raises ``KeyError`` when that shard
        holds no cached block — callers gate on
        :meth:`cached_in_shard`."""
        if shard is None:
            b, _ = self._evictable.popitem(last=False)
        else:
            b = next(x for x in self._evictable
                     if x // self.blocks_per_shard == shard)
            del self._evictable[b]
        h = self._block_to_hash.pop(b)
        del self._hash_to_block[h]
        owner = self._cached_owner.pop(b, None)
        if self.spill_store is not None and self._spill_fetch is not None:
            payload = self._spill_fetch(b)
            if payload is not None:
                self.spill_store.put(h, payload,
                                     tenant=owner or DEFAULT_TENANT)
        if owner is not None:
            counter = (self._flushed_by_tenant if flushed
                       else self._evicted_by_tenant)
            counter[owner] = counter.get(owner, 0) + 1
        self.num_evictions += 1
        return b

    def alloc(self, n: int, tenant: str = DEFAULT_TENANT,
              shard: Optional[int] = None) -> List[int]:
        """Hand out ``n`` blocks at refcount 1 (charged to ``tenant``),
        evicting LRU cached blocks when the free list alone cannot
        serve the request. ``shard`` restricts the allocation to one
        shard's contiguous id range (the batch-axis engine allocates a
        lane's blocks only on the lane's shard); a shard-scoped
        request that cannot be served from THAT shard raises
        ``CacheOutOfBlocks`` even when other shards hold free blocks —
        cross-shard placement would break the collective-free device
        split. ``shard=None`` (and every single-shard allocator) is
        the pre-batch-axis path, bit for bit."""
        if shard is None or self.num_shards == 1:
            if n > len(self._free) + len(self._evictable):
                raise CacheOutOfBlocks(
                    f"requested {n} blocks, {len(self._free)} free + "
                    f"{len(self._evictable)} evictable of "
                    f"{self.num_blocks}")
            out = []
            for _ in range(n):
                b = self._free.pop() if self._free else self._evict_one()
                self._ref[b] = 1
                self._tenant_refs[b] = {tenant: 1}
                self._charge_block(b, +1)
                out.append(b)
            return out
        shard = int(shard)
        if not 0 <= shard < self.num_shards:
            raise ValueError(
                f"shard {shard} out of range [0, {self.num_shards})")
        free_s = self.free_in_shard(shard)
        if n > free_s + self.cached_in_shard(shard):
            raise CacheOutOfBlocks(
                f"requested {n} blocks on shard {shard}, {free_s} free "
                f"+ {self.cached_in_shard(shard)} evictable of "
                f"{self.blocks_per_shard} shard blocks")
        out = []
        for _ in range(n):
            b = None
            # same LIFO discipline as the unsharded pop(): the most
            # recently freed block of the shard serves first
            for i in range(len(self._free) - 1, -1, -1):
                if self._free[i] // self.blocks_per_shard == shard:
                    b = self._free.pop(i)
                    break
            if b is None:
                b = self._evict_one(shard=shard)
            self._ref[b] = 1
            self._tenant_refs[b] = {tenant: 1}
            self._charge_block(b, +1)
            out.append(b)
        return out

    def free(self, ids: Sequence[int], tenant: str = DEFAULT_TENANT) -> None:
        """Release one of ``tenant``'s references per id. A registered
        block whose count hits zero is retained as cached (evictable);
        an unregistered one returns to the free list. Raises
        ``ValueError`` on an unknown block id, a double free (releasing
        a block that holds no reference), or a tenant releasing a
        reference it never took, instead of silently corrupting the
        free list or the tenant ledger."""
        for b in ids:
            b = int(b)
            if not (0 <= b < self.num_blocks):
                raise ValueError(f"block id {b} out of range")
            if self._ref.get(b, 0) <= 0:
                raise ValueError(f"double free of block {b}")
            holders = self._tenant_refs[b]
            if holders.get(tenant, 0) <= 0:
                raise ValueError(
                    f"tenant {tenant!r} holds no reference on block {b} "
                    f"(holders: {holders})")
            self._charge_block(b, -1)
            holders[tenant] -= 1
            if holders[tenant] == 0:
                del holders[tenant]
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                del self._tenant_refs[b]
                if b in self._block_to_hash:
                    self._evictable[b] = None      # most-recently-used end
                else:
                    self._free.append(b)
            else:
                self._charge_block(b, +1)

    def acquire(self, ids: Sequence[int],
                tenant: str = DEFAULT_TENANT) -> None:
        """Add one reference per id for ``tenant`` (prefix sharing).
        Revives cached (refcount-0) blocks; raises for blocks that are
        neither active nor cached — a free block holds no meaningful
        contents."""
        for b in ids:
            b = int(b)
            if self._ref.get(b, 0) > 0:
                self._charge_block(b, -1)
                self._ref[b] += 1
                holders = self._tenant_refs[b]
                holders[tenant] = holders.get(tenant, 0) + 1
                self._charge_block(b, +1)
            elif b in self._evictable:
                del self._evictable[b]
                self._ref[b] = 1
                self._tenant_refs[b] = {tenant: 1}
                self._charge_block(b, +1)
            else:
                raise ValueError(
                    f"cannot acquire block {b}: neither active nor cached")

    # -- the prefix index --------------------------------------------------

    def register_prefix(self, block_hash: str, block_id: int,
                        tenant: str = DEFAULT_TENANT) -> bool:
        """Index a FULL block's contents under its chain hash. First
        registration wins — a concurrent identical prefill keeps the
        already-indexed block and leaves the duplicate unregistered (it
        returns to the free list when released). The winning
        registration records ``tenant`` as the block's cached-state
        owner: if the block is ever evicted or flushed while cached,
        THAT tenant is charged. Returns whether this block is now the
        indexed one."""
        block_id = int(block_id)
        if block_hash in self._hash_to_block:
            return self._hash_to_block[block_hash] == block_id
        if block_id in self._block_to_hash:   # already indexed elsewhere
            return False
        self._hash_to_block[block_hash] = block_id
        self._block_to_hash[block_id] = block_hash
        self._cached_owner[block_id] = tenant
        if self.spill_store is not None:
            # a device block now serves this hash: the host copy is
            # redundant (and would violate the disjointness invariant
            # check_integrity enforces) — a fresh recompute registering
            # the same content supersedes the spilled copy
            self.spill_store.discard(block_hash)
        return True

    def indexed_block(self, block_hash: str) -> Optional[int]:
        """The device block currently serving a chain hash, or None —
        the read-only point lookup behind the fleet router's affinity
        probe and the migration transport's device-vs-spill split."""
        return self._hash_to_block.get(block_hash)

    def lookup_prefix(self, hashes: Sequence[str],
                      shard: Optional[int] = None) -> List[int]:
        """Longest indexed prefix of the hash chain, WITHOUT taking
        references — for capacity checks before committing to an
        admission (no rollback, no LRU perturbation). ``shard`` stops
        the walk at the first block OUTSIDE that shard's id range: a
        batch-axis lane can only share blocks resident on its own
        shard (a cross-shard match would put a foreign block id in a
        table the sharded program cannot reach)."""
        out: List[int] = []
        for h in hashes:
            b = self._hash_to_block.get(h)
            if b is None:
                break
            if (shard is not None
                    and b // self.blocks_per_shard != shard):
                break
            out.append(b)
        return out

    def match_prefix(self, hashes: Sequence[str],
                     tenant: str = DEFAULT_TENANT,
                     shard: Optional[int] = None) -> List[int]:
        """Longest indexed prefix of the hash chain: returns the block
        ids (in sequence order) and acquires a reference on each for
        ``tenant`` — callers own the returned blocks and must ``free``
        them under the same tenant. ``shard`` applies the
        :meth:`lookup_prefix` shard restriction."""
        out = self.lookup_prefix(hashes, shard=shard)
        self.acquire(out, tenant=tenant)
        return out

    def trim_to(self, blocks: Sequence[int], keep: int,
                tenant: str = DEFAULT_TENANT) -> List[int]:
        """Release the tail of a sequence's block list past its first
        ``keep`` entries and return the kept prefix as a new list — the
        **speculative-reservation rollback**: the engine reserves
        blocks for a verify span's worst case (every draft written),
        and when rejection leaves the sequence short of the span, the
        blocks holding only unaccepted positions go back to the pool
        here instead of idling on the slot until the request finishes.

        Safety contract, enforced: a trimmed block must be PRIVATE
        (refcount exactly 1) and UNREGISTERED — a shared or
        prefix-indexed block holds context some sequence (or the cache
        index) still reaches, and trimming it would be a use-after-free
        of live K/V. Violations raise ``ValueError`` before anything is
        released. The tail is freed deepest-first, matching the other
        release paths."""
        blocks = [int(b) for b in blocks]
        keep = int(keep)
        if not 0 <= keep <= len(blocks):
            raise ValueError(
                f"keep must be in [0, {len(blocks)}], got {keep}")
        tail = blocks[keep:]
        for b in tail:
            if self._ref.get(b, 0) != 1:
                raise ValueError(
                    f"cannot trim block {b}: refcount "
                    f"{self._ref.get(b, 0)} != 1 (shared or not owned)")
            if b in self._block_to_hash:
                raise ValueError(
                    f"cannot trim block {b}: registered in the prefix "
                    "index (it is matchable cached context)")
        self.free(list(reversed(tail)), tenant=tenant)
        return blocks[:keep]

    def flush_evictable(self) -> int:
        """Evict EVERY cached (refcount-0, prefix-indexed) block back
        to the free list — the degradation ladder's aggressive-eviction
        rung (docs/robustness.md): under sustained pool pressure the
        engine trades future prefix hits for immediately-allocatable
        headroom. Each drop counts as an eviction (the blocks really do
        leave the index) and charges the registering tenant's flush
        counter. Returns how many blocks were flushed."""
        n = len(self._evictable)
        while self._evictable:
            self._free.append(self._evict_one(flushed=True))
        return n

    def reset(self) -> None:
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._ref.clear()
        self._hash_to_block.clear()
        self._block_to_hash.clear()
        self._evictable.clear()
        self._tenant_refs.clear()
        self._cached_owner.clear()
        self._tenant_charge_acc.clear()
        # the eviction/flush attribution counters deliberately survive:
        # reset is the crash-recovery path, and observability should
        # not lose history to it (matching num_evictions)

    # -- robustness: audit + integrity (docs/robustness.md) ----------------

    def snapshot_state(self) -> Dict[str, object]:
        """JSON-serializable picture of the allocator: refcounts, the
        prefix index, the evictable LRU order, and the free list. This
        is the AUDIT section of an engine snapshot — block ids and the
        KV contents behind them do not survive a process, so restore
        rebuilds allocator state from re-prefills rather than loading
        this (tests verify the rebuild reproduces the same hash chains
        and refcount structure)."""
        return {
            "refcounts": {str(b): int(c) for b, c in self._ref.items()},
            "prefix_index": dict(self._hash_to_block),
            "evictable": [int(b) for b in self._evictable],
            "free": [int(b) for b in self._free],
            "num_evictions": int(self.num_evictions),
            "tenant_refs": {str(b): dict(refs)
                            for b, refs in self._tenant_refs.items()},
            "cached_owners": {str(b): t
                              for b, t in self._cached_owner.items()},
            "evicted_by_tenant": dict(self._evicted_by_tenant),
            "flushed_by_tenant": dict(self._flushed_by_tenant),
        }

    def check_integrity(self, expected_refcounts: Optional[Dict[int, int]]
                        = None,
                        expected_tenant_refs: Optional[
                            Dict[int, Dict[str, int]]] = None) -> None:
        """Raise ``ValueError`` on any violated allocator invariant:
        every block in exactly one of {free, active, cached}; the
        hash↔block maps a bijection; cached blocks registered at
        refcount 0; the per-tenant reference split summing exactly to
        each block's refcount; and, when the caller supplies the
        refcounts its own bookkeeping implies (one per sequence
        referencing the block — optionally split by tenant), an EXACT
        match against the internal counts."""
        free, active = set(self._free), set(self._ref)
        cached = set(self._evictable)
        if len(free) != len(self._free):
            raise ValueError("free list contains duplicates")
        for name, ids in (("free", free), ("active", active),
                          ("cached", cached)):
            bad = [b for b in ids if not 0 <= b < self.num_blocks]
            if bad:
                raise ValueError(f"{name} ids out of range: {bad}")
        overlaps = (free & active) | (free & cached) | (active & cached)
        if overlaps:
            raise ValueError(f"blocks in multiple states: {sorted(overlaps)}")
        if len(free) + len(active) + len(cached) != self.num_blocks:
            raise ValueError(
                f"state partition covers {len(free) + len(active) + len(cached)}"
                f" of {self.num_blocks} blocks")
        if any(c <= 0 for c in self._ref.values()):
            raise ValueError("active block with non-positive refcount")
        inv = {b: h for h, b in self._hash_to_block.items()}
        if inv != self._block_to_hash:
            raise ValueError("prefix index hash<->block maps disagree")
        unregistered = cached - set(self._block_to_hash)
        if unregistered:
            raise ValueError(
                f"cached blocks missing from the index: {sorted(unregistered)}")
        registered_free = free & set(self._block_to_hash)
        if registered_free:
            raise ValueError(
                f"free blocks still indexed: {sorted(registered_free)}")
        if set(self._tenant_refs) != active:
            raise ValueError(
                f"tenant-ref map keys {sorted(self._tenant_refs)} != "
                f"active blocks {sorted(active)}")
        for b, refs in self._tenant_refs.items():
            if any(c <= 0 for c in refs.values()):
                raise ValueError(
                    f"block {b}: non-positive tenant refcount {refs}")
            if sum(refs.values()) != self._ref[b]:
                raise ValueError(
                    f"block {b}: tenant refs {refs} sum to "
                    f"{sum(refs.values())}, refcount is {self._ref[b]}")
        stray_owner = set(self._cached_owner) - set(self._block_to_hash)
        if stray_owner:
            raise ValueError(
                f"cached-owner entries for unregistered blocks: "
                f"{sorted(stray_owner)}")
        # the host spill tier must stay disjoint from the device index
        # (a hash served by a resident block has no business holding a
        # host copy — re-admission pops, re-registration discards) and
        # within its configured byte bound
        if self.spill_store is not None:
            overlap = (set(self.spill_store.hashes())
                       & set(self._hash_to_block))
            if overlap:
                raise ValueError(
                    f"{len(overlap)} hash(es) both device-indexed and "
                    f"spilled (e.g. {sorted(overlap)[:2]})")
            if self.spill_store.total_bytes > self.spill_store.max_bytes:
                raise ValueError(
                    f"spill store holds {self.spill_store.total_bytes} "
                    f"bytes, over its {self.spill_store.max_bytes} bound")
        # the incremental charge accumulator must track the exact
        # per-block sums (within float tolerance); verified then
        # REBASED to the exact values so drift never accumulates
        # across integrity checkpoints
        exact: Dict[str, float] = {}
        for b, refs in self._tenant_refs.items():
            for t, n in refs.items():
                exact[t] = exact.get(t, 0.0) \
                    + self.block_weight * n / self._ref[b]
        for t in set(exact) | set(self._tenant_charge_acc):
            if abs(exact.get(t, 0.0)
                   - self._tenant_charge_acc.get(t, 0.0)) > 1e-6:
                raise ValueError(
                    f"tenant {t!r}: incremental charge "
                    f"{self._tenant_charge_acc.get(t, 0.0)} diverged "
                    f"from exact {exact.get(t, 0.0)}")
        self._tenant_charge_acc = exact
        if expected_tenant_refs is not None:
            expect = {int(b): {t: int(c) for t, c in refs.items() if c > 0}
                      for b, refs in expected_tenant_refs.items()}
            expect = {b: refs for b, refs in expect.items() if refs}
            if expect != self._tenant_refs:
                raise ValueError(
                    f"tenant refs diverge from caller bookkeeping: "
                    f"expected {expect}, allocator holds "
                    f"{self._tenant_refs}")
        if expected_refcounts is not None:
            expected = {int(b): int(c) for b, c in expected_refcounts.items()
                        if int(c) > 0}
            if expected != self._ref:
                raise ValueError(
                    f"refcounts diverge from caller bookkeeping: "
                    f"expected {expected}, allocator holds {self._ref}")


def blocks_needed(num_tokens: int, block_size: int) -> int:
    return -(-int(num_tokens) // int(block_size))


def seq_block_hashes(tokens: Sequence[int],
                     block_size: int) -> List[str]:
    """The chain-hash walk over a token sequence's FULL blocks — the
    one shared builder behind the engine's prefix matching and the
    fleet router's affinity probe / migration transport (two copies
    drifting apart would silently break cross-replica hash
    comparability)."""
    hashes: List[str] = []
    prev = None
    for j in range(len(tokens) // block_size):
        prev = hash_block_tokens(
            prev, tokens[j * block_size: (j + 1) * block_size])
        hashes.append(prev)
    return hashes


class HostSpillStore:
    """The host-RAM spill tier of the prefix cache (docs/serving.md
    memory tiers): a bounded LRU of evicted prefix blocks, keyed by
    the SHA-256 chain hash the device index uses — hashes are globally
    comparable, so a spilled block is re-admittable by ANY engine with
    the same model/config (the fleet-migration enabler ROADMAP item 2
    names).

    Each entry is one block's full device contents as host numpy
    arrays: ``{"k": [L, bs, H, D], "v": [L, bs, H, D]}`` in the pool's
    storage dtype, plus ``"k_scale"``/``"v_scale"`` (``[L, bs, H]``
    fp32) for quantized pools — a spilled quantized block re-admits
    bit-identically, scales included. ``max_bytes`` bounds the payload
    total; inserts evict least-recently-used entries past it (and an
    entry larger than the whole bound is dropped on arrival, counted
    as an eviction).

    The store is an OPTIMIZATION tier, never identity: entries are
    audit-only in ``snapshot()`` (restore never reads them), a miss
    just means recompute, and a hit is token-identical to recompute
    (the re-admit equivalence cert in tests/test_kv_memory.py).

    **Integrity** (docs/robustness.md, "Data integrity"): with
    ``verify=True`` every entry stores a SHA-256 content checksum
    taken at :meth:`put`, re-checked at every read (:meth:`pop` /
    :meth:`export_entry`) and by the background :meth:`scrub` — a
    mismatch (host-RAM rot, a corrupted copy) discards the entry,
    counts it (``corrupt_discards``), reports it through
    ``on_corrupt(site, block_hash)``, and reads as a plain miss: the
    tier's whole contract is that a miss means recompute, so detection
    degrades to correctness, never to an error. ``corrupt_hook(site,
    payload) -> payload`` is the chaos seam (the engine wires its
    :class:`~apex_tpu.utils.faults.FaultPlan`'s ``"spill_put"`` /
    ``"spill_get"`` corrupt sites through it); with ``verify=False``
    no checksum is taken and reads trust their bytes — byte-identical
    to the pre-integrity store."""

    def __init__(self, max_bytes: int, verify: bool = True,
                 corrupt_hook=None, on_corrupt=None):
        if max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1, got {max_bytes}")
        self.max_bytes = int(max_bytes)
        self.verify = bool(verify)
        self._corrupt_hook = corrupt_hook
        self._on_corrupt = on_corrupt
        # hash -> {"payload": dict of np arrays, "tenant": str,
        # "bytes": int, "checksum": str|None}; insertion order = LRU
        # order (puts re-insert)
        self._entries: "OrderedDict[str, Dict[str, object]]" = \
            OrderedDict()
        self.total_bytes = 0
        self.puts = 0          # lifetime blocks spilled in
        self.evictions = 0     # entries dropped by the byte bound
        self.refused = 0           # oversize entries never admitted
        self.corrupt_discards = 0  # entries dropped on checksum mismatch
        self._scrub_cursor = 0     # round-robin position of scrub()

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, block_hash: str) -> bool:
        return block_hash in self._entries

    def hashes(self):
        return self._entries.keys()

    def entry_tenants(self) -> Dict[str, str]:
        """Chain hash -> owning tenant for every resident entry — the
        fleet router's shared-tier publish sweep reads this to carry
        attribution across the transport (JSON-friendly: part of the
        narrow replica surface)."""
        return {h: str(rec["tenant"])
                for h, rec in self._entries.items()}

    def _drop(self, block_hash: str) -> None:
        rec = self._entries.pop(block_hash)
        self.total_bytes -= rec["bytes"]

    def put(self, block_hash: str, payload: Dict[str, np.ndarray],
            tenant: str = DEFAULT_TENANT) -> bool:
        """Insert (or refresh) a block's contents at the MRU end,
        evicting LRU entries past the byte bound. Returns whether the
        entry is resident after the call."""
        nbytes = sum(int(a.nbytes) for a in payload.values()
                     if isinstance(a, np.ndarray))
        if block_hash in self._entries:
            self._drop(block_hash)
        self.puts += 1
        if nbytes > self.max_bytes:
            self.evictions += 1
            self.refused += 1
            return False
        # checksum the TRUE bytes first, then let the chaos hook rot
        # them — exactly the order real corruption happens in (the
        # checksum is taken at the source; the flip happens in RAM)
        checksum = payload_checksum(payload) if self.verify else None
        if self._corrupt_hook is not None:
            payload = self._corrupt_hook("spill_put", payload)
        self._entries[block_hash] = {
            "payload": payload, "tenant": tenant, "bytes": nbytes,
            "checksum": checksum}
        self.total_bytes += nbytes
        while self.total_bytes > self.max_bytes:
            # every removal funnels through _drop so subclasses that
            # keep per-entry side tables (SharedPrefixStore's refcounts
            # and ownership shares) stay consistent under eviction
            self._drop(next(iter(self._entries)))
            self.evictions += 1
        return block_hash in self._entries

    def _read_ok(self, block_hash: str, payload, checksum) -> bool:
        """The shared read-side verification: recompute the payload's
        checksum against the one taken at put. A mismatch counts as a
        corrupt discard and reports through ``on_corrupt`` — the
        caller turns it into a miss (recompute serves the request)."""
        if not self.verify or checksum is None:
            return True
        if payload_checksum(payload) == checksum:
            return True
        self.corrupt_discards += 1
        if self._on_corrupt is not None:
            self._on_corrupt("spill_get", block_hash)
        return False

    def pop(self, block_hash: str) -> Optional[Dict[str, np.ndarray]]:
        """Remove and return a block's payload (None on miss OR on a
        checksum mismatch — a corrupt entry is discarded, counted, and
        served by recompute) — the re-admission read. Popping (rather
        than peeking) keeps the store disjoint from the device index:
        the caller is about to upload and register a device block
        under this hash."""
        rec = self._entries.get(block_hash)
        if rec is None:
            return None
        self._drop(block_hash)
        payload = rec["payload"]
        if self._corrupt_hook is not None:
            payload = self._corrupt_hook("spill_get", payload)
        if not self._read_ok(block_hash, payload, rec.get("checksum")):
            return None
        return payload

    def discard(self, block_hash: str) -> None:
        if block_hash in self._entries:
            self._drop(block_hash)

    # -- cross-replica transport (docs/fleet.md) ---------------------------

    def export_entry(self, block_hash: str
                     ) -> Optional[Dict[str, np.ndarray]]:
        """A deep-copied payload for cross-replica transport (None on
        miss). A PEEK, not a pop: the entry stays resident here (the
        exporting replica keeps serving it) and its LRU recency is
        untouched — chain hashes are globally comparable, so the copy
        is re-admittable by any engine with the same model/config
        (:meth:`import_entry` on the receiving store)."""
        rec = self._entries.get(block_hash)
        if rec is None:
            return None
        payload = {k: np.array(v, copy=True)
                   for k, v in rec["payload"].items()}
        if self._corrupt_hook is not None:
            payload = self._corrupt_hook("spill_get", payload)
        if not self._read_ok(block_hash, payload, rec.get("checksum")):
            # rot detected on the read: the resident entry is no
            # longer trustworthy either — discard it (a future local
            # hit would re-detect anyway; dropping now keeps the
            # byte accounting honest)
            self._drop(block_hash)
            return None
        return payload

    def import_entry(self, block_hash: str,
                     payload: Dict[str, np.ndarray],
                     tenant: str = DEFAULT_TENANT) -> bool:
        """Insert a payload exported by another replica's store (or
        read from its device pool): validated for the K/V keys, then
        standard :meth:`put` semantics — MRU insert, byte-bound LRU
        eviction. Returns whether the entry is resident after the
        call. The importing engine's next prefix match re-admits it by
        device upload, token-identical to recompute (the migration
        transport's correctness rests on the same re-admit cert as
        local spill hits)."""
        missing = [k for k in ("k", "v") if k not in payload]
        if missing:
            raise ValueError(
                f"imported payload for {block_hash!r} is missing "
                f"{missing} (expected the block's K/V arrays)")
        return self.put(block_hash, payload, tenant=tenant)

    def scrub(self, n: int) -> Tuple[int, int]:
        """Re-verify up to ``n`` resident entries against their put-time
        checksums, round-robin from where the last scrub stopped — the
        background integrity pass (docs/robustness.md): rot in a COLD
        entry is found while recompute is still cheap, not at the
        admission that needed it. Corrupt entries are discarded and
        counted exactly like a read-side detection. Returns
        ``(entries_verified, corruptions_found)``; (0, 0) with
        verification off or an empty store."""
        if not self.verify or n < 1 or not self._entries:
            return (0, 0)
        hashes = list(self._entries.keys())
        start = self._scrub_cursor % len(hashes)
        scanned = min(int(n), len(hashes))
        verified = corrupt = 0
        for j in range(scanned):
            h = hashes[(start + j) % len(hashes)]
            rec = self._entries.get(h)
            if rec is None or rec.get("checksum") is None:
                continue
            verified += 1
            if payload_checksum(rec["payload"]) != rec["checksum"]:
                self._drop(h)
                self.corrupt_discards += 1
                corrupt += 1
                if self._on_corrupt is not None:
                    self._on_corrupt("scrub", h)
        self._scrub_cursor = start + scanned
        return (verified, corrupt)

    def stats(self) -> Dict[str, int]:
        return {
            "blocks": len(self._entries),
            "bytes": int(self.total_bytes),
            "puts": int(self.puts),
            "evictions": int(self.evictions),
            # the uniform refusal/corruption surface (docs/robustness.md
            # "Data integrity"): oversize entries never admitted, and
            # entries dropped on a checksum mismatch
            "refused": int(self.refused),
            "corrupt_discards": int(self.corrupt_discards),
        }


class SharedPrefixStore(HostSpillStore):
    """The FLEET-level shared prefix tier (docs/fleet.md, "Shared
    prefix tier"): one byte-budgeted, content-addressed store the
    router owns, fed by replica evictions and finished-prefill
    handoffs, probed at placement so a prefix prefilled on any replica
    is warm fleet-wide. Same checksummed-entry discipline as the
    per-replica :class:`HostSpillStore` it extends — put-time SHA-256
    checksums re-verified at every read and by the round-robin
    :meth:`scrub`, corrupt entries discarded-and-recomputed, LRU past
    ``max_bytes`` — plus the two things a SHARED tier needs:

    **Refcounted dedupe.** Entries are content-addressed by chain
    hash, so the same prefix published from two replicas stores ONCE:
    a re-publish of a resident hash adds a reference (and an ownership
    share) instead of bytes, counted in ``dedupe_hits``. Eviction and
    corruption discards drop the entry with all its references — the
    tier is a cache, and a reference is attribution, not a pin.

    **Fractional ownership attribution.** Each entry carries per-tenant
    publisher shares; :meth:`tenant_bytes` charges an entry's bytes to
    its owning tenants proportionally (the fractional block ledger
    discipline, applied to the shared tier), which is what the fleet's
    ``stats()["tenants"]`` ``shared_tier_bytes`` rows read.
    :meth:`check_integrity` audits the refcount/share/byte invariants
    the same way the allocator audits its ledger."""

    def __init__(self, max_bytes: int, verify: bool = True,
                 corrupt_hook=None, on_corrupt=None):
        super().__init__(max_bytes, verify=verify,
                         corrupt_hook=corrupt_hook,
                         on_corrupt=on_corrupt)
        # per-resident-hash publisher refcount, and the per-tenant
        # share split of that refcount (sums to it; audited)
        self._refs: Dict[str, int] = {}
        self._owners: Dict[str, Dict[str, int]] = {}
        self.dedupe_hits = 0   # publishes deduped against a resident entry

    def _drop(self, block_hash: str) -> None:
        super()._drop(block_hash)
        self._refs.pop(block_hash, None)
        self._owners.pop(block_hash, None)

    def publish(self, block_hash: str,
                payload: Optional[Dict[str, np.ndarray]] = None,
                tenant: str = DEFAULT_TENANT) -> bool:
        """Content-addressed insert with refcounted dedupe. A resident
        hash gains a reference and an ownership share — no bytes
        stored, no payload needed (``payload=None`` is the publisher
        saying "I hold these bytes too"), and the entry refreshes to
        MRU (a re-publish is evidence of fleet-wide heat). A new hash
        needs its payload and follows :meth:`HostSpillStore.put`
        semantics (checksum at the source, byte-bound LRU eviction).
        Returns whether the entry is resident after the call."""
        if block_hash in self._entries:
            self.dedupe_hits += 1
            self._refs[block_hash] += 1
            shares = self._owners[block_hash]
            shares[tenant] = shares.get(tenant, 0) + 1
            self._entries.move_to_end(block_hash)
            return True
        if payload is None:
            return False
        if self.put(block_hash, payload, tenant=tenant):
            self._refs[block_hash] = 1
            self._owners[block_hash] = {tenant: 1}
            return True
        return False

    def fetch(self, block_hash: str
              ) -> Optional[Dict[str, np.ndarray]]:
        """A deep-copied payload for seeding a replica's local spill
        tier (None on miss or checksum mismatch — a corrupt entry is
        discarded with its references and served by recompute). A PEEK
        like :meth:`export_entry` — the tier keeps serving the other
        replicas — but a fetch IS a hit, so the entry refreshes to MRU
        (export_entry's transport reads deliberately do not)."""
        payload = self.export_entry(block_hash)
        if payload is not None:
            self._entries.move_to_end(block_hash)
        return payload

    def probe(self, hashes: Sequence[str], start: int = 0) -> int:
        """Length of the contiguous resident run of ``hashes``
        beginning at ``start`` — the placement-time coverage probe
        (read-only; same leading-run discipline as the engine's
        prefix match)."""
        n = int(start)
        while n < len(hashes) and hashes[n] in self._entries:
            n += 1
        return n - int(start)

    def tenant_bytes(self) -> Dict[str, float]:
        """Per-tenant fractional byte charge: each entry's bytes split
        across its owning tenants by publisher share (an entry two
        tenants each published once charges half to each)."""
        out: Dict[str, float] = {}
        for h, rec in self._entries.items():
            refs = self._refs.get(h, 1)
            for t, n in (self._owners.get(h) or {}).items():
                out[t] = out.get(t, 0.0) + rec["bytes"] * n / refs
        return {t: round(v, 6) for t, v in out.items()}

    def check_integrity(self) -> None:
        """Audit the refcount/ownership/byte invariants (raises
        ``ValueError`` — a violated shared ledger has no safe
        degradation): every resident entry has a positive refcount
        whose per-tenant shares sum to it exactly, no side-table row
        outlives its entry, and the byte accumulator equals the sum of
        resident entry sizes within the budget."""
        total = sum(int(rec["bytes"]) for rec in self._entries.values())
        if total != self.total_bytes:
            raise ValueError(
                f"shared tier byte accumulator {self.total_bytes} != "
                f"sum of resident entries {total}")
        if self.total_bytes > self.max_bytes:
            raise ValueError(
                f"shared tier holds {self.total_bytes} bytes over its "
                f"budget {self.max_bytes}")
        for name, table in (("refcount", self._refs),
                            ("ownership", self._owners)):
            if set(table) != set(self._entries):
                stray = set(table) ^ set(self._entries)
                raise ValueError(
                    f"shared tier {name} table out of sync with the "
                    f"resident entries (mismatched hashes: "
                    f"{sorted(stray)[:3]})")
        for h, refs in self._refs.items():
            if refs < 1:
                raise ValueError(
                    f"shared entry {h!r} has refcount {refs} < 1")
            shares = self._owners[h]
            if any(n < 1 for n in shares.values()):
                raise ValueError(
                    f"shared entry {h!r} has a non-positive ownership "
                    f"share: {shares}")
            if sum(shares.values()) != refs:
                raise ValueError(
                    f"shared entry {h!r} ownership shares {shares} do "
                    f"not sum to its refcount {refs}")

    def stats(self) -> Dict[str, int]:
        out = super().stats()
        out["dedupe_hits"] = int(self.dedupe_hits)
        return out


class DeviceMirror:
    """A dirty-tracked host→device buffer: the device copy of host state
    that changes RARELY relative to how often it is consumed.

    The serving engine ships a ``[max_batch, max_blocks_per_seq]`` block
    table and a handful of per-lane sampling arrays into every decode
    dispatch. Their contents change only when the SLOT COMPOSITION
    changes (admission, finish, preemption, block growth) — not on the
    steady-state tick — yet the pre-mirror engine rebuilt and re-uploaded
    them from scratch every ``step()``. A mirror caches the built device
    value and rebuilds only after :meth:`invalidate`:

        mirror.get(build_fn)   # cached device value, or build_fn() once
        mirror.invalidate()    # host state changed; next get() rebuilds

    Pure host-side bookkeeping (no jax calls of its own): ``build_fn``
    owns the upload, the mirror owns only the decision to skip it. The
    scheduler invalidates at its mutation points; forgetting one is a
    correctness bug (a stale table scatters K/V into freed blocks), so
    mutation sites funnel through the engine's ``_invalidate_*``
    helpers rather than touching mirrors directly.
    """

    __slots__ = ("_value",)

    def __init__(self):
        self._value = None

    @property
    def dirty(self) -> bool:
        return self._value is None

    def invalidate(self) -> None:
        self._value = None

    def get(self, build):
        if self._value is None:
            self._value = build()
        return self._value


def device_block_table(host_tables: np.ndarray, num_blocks: int) -> jax.Array:
    """Host tables use -1 for unallocated entries; the device convention
    is ``num_blocks`` (one past the pool) so scatters drop and gathers
    clip into already-masked positions."""
    t = np.asarray(host_tables, np.int32)
    return jnp.asarray(np.where(t >= 0, t, num_blocks), jnp.int32)


def _page_offsets(block_tables: jax.Array, positions: jax.Array,
                  valid: jax.Array, N: int, bs: int):
    """(page, off) scatter coordinates for per-token block writes;
    invalid positions route to the out-of-bounds page ``N`` so the
    caller's ``mode="drop"`` scatter discards them."""
    page = jnp.take_along_axis(block_tables, positions // bs, axis=1)
    page = jnp.where(valid, page, N)
    return page, positions % bs


def paged_write(pages: jax.Array, layer: int, block_tables: jax.Array,
                positions: jax.Array, values: jax.Array,
                valid: jax.Array) -> jax.Array:
    """Scatter per-token K or V into one layer's blocks.

    Args:
      pages: the full pool ``[L, N, bs, H, D]``.
      layer: static layer index.
      block_tables: ``[B, max_blocks_per_seq]`` int32 (device
        convention: out-of-bounds id for unallocated entries).
      positions: ``[B, S]`` absolute token positions within each
        sequence.
      values: ``[B, S, H, D]`` the tokens' K or V heads.
      valid: ``[B, S]`` bool; False routes the write out of bounds,
        where ``mode="drop"`` discards it (padding tokens, inactive
        decode slots, already-cached prefix positions).
    """
    N, bs = pages.shape[1], pages.shape[2]
    page, off = _page_offsets(block_tables, positions, valid, N, bs)
    return pages.at[layer, page, off].set(
        values.astype(pages.dtype), mode="drop")


def quantize_kv_rows(values: jax.Array, positions: jax.Array,
                     quantization: str, stream: int = 0):
    """Quantize ``[B, S, H, D]`` K/V rows to the storage dtype.

    Per (token, head) row: ``scale = max|row| / qmax`` (qmax = 127 for
    int8, the fp8 finite max for fp8), payload = the scaled row rounded
    into storage. int8 rounding is STOCHASTIC via
    :func:`apex_tpu.ops.multi_tensor.stochastic_round`, keyed by
    ``(stream, absolute position)`` (``positions``, ``[B, S]``) — a
    pure function of (value, stream, position), so re-prefilling the
    same token after preemption/restore reproduces the identical
    quantized bytes and the engine's resume-determinism contract
    survives quantization. ``stream`` decorrelates consumers sharing
    positions: :func:`write_kv` tags each (layer, K-vs-V) pair with
    its own stream, so a token's K and V rows — and its rows across
    layers — draw INDEPENDENT rounding noise (correlated noise would
    compound in one direction through the network instead of
    averaging out; determinism only needs the stream to be a static
    property of the call site, which (layer, k/v) is). fp8 rounds by
    the cast (round-to-nearest; its mantissa keeps relative error, so
    stochastic bits buy nothing).

    Returns ``(payload [B, S, H, D] storage-dtype, scales [B, S, H]
    fp32)``; an all-zero row stores payload 0 with scale 0 (dequant
    reproduces the zeros exactly).
    """
    from apex_tpu.ops.multi_tensor import stochastic_round

    dt = _quant_storage_dtype(quantization)
    qmax = _quant_value_max(quantization)
    v32 = values.astype(jnp.float32)
    amax = jnp.max(jnp.abs(v32), axis=-1)              # [B, S, H]
    scale = amax / qmax
    safe = jnp.where(scale > 0, scale, 1.0)
    x = v32 / safe[..., None]
    if quantization == "fp8":
        return x.astype(dt), scale
    B, S = positions.shape
    base = jax.random.fold_in(jax.random.PRNGKey(_KV_QUANT_SEED),
                              int(stream))
    keys = jax.vmap(lambda p: jax.random.fold_in(base, p))(
        positions.reshape(-1))
    q = jax.vmap(lambda row, key: stochastic_round(row, dt, key))(
        x.reshape((B * S,) + x.shape[2:]), keys)
    return q.reshape(x.shape), scale


def write_kv(cache: KVCache, layer: int, block_tables: jax.Array,
             positions: jax.Array, k_values: jax.Array,
             v_values: jax.Array, valid: jax.Array) -> KVCache:
    """Scatter one layer's K AND V rows into the pool, quantizing on
    the way in when the pool stores quantized blocks (payload + scales
    land through the same ``(page, off)`` coordinates, so a block's
    scales always travel with its bytes). The full-precision path is
    exactly two :func:`paged_write` calls — bit-identical to the
    pre-quantization write."""
    mode = cache.quantization
    if mode is None:
        return cache._replace(
            k=paged_write(cache.k, layer, block_tables, positions,
                          k_values, valid),
            v=paged_write(cache.v, layer, block_tables, positions,
                          v_values, valid))
    N, bs = cache.k.shape[1], cache.k.shape[2]
    page, off = _page_offsets(block_tables, positions, valid, N, bs)
    # distinct rounding streams per (layer, K-vs-V): same positions,
    # independent noise (see quantize_kv_rows)
    qk, sk = quantize_kv_rows(k_values, positions, mode,
                              stream=2 * layer)
    qv, sv = quantize_kv_rows(v_values, positions, mode,
                              stream=2 * layer + 1)
    return KVCache(
        k=cache.k.at[layer, page, off].set(qk, mode="drop"),
        v=cache.v.at[layer, page, off].set(qv, mode="drop"),
        k_scale=cache.k_scale.at[layer, page, off].set(sk, mode="drop"),
        v_scale=cache.v_scale.at[layer, page, off].set(sv, mode="drop"))


def gather_kv(pages: jax.Array, layer: int,
              block_tables: jax.Array) -> jax.Array:
    """Read every sequence's cached tokens back out of one layer's pool:
    ``[B, max_blocks_per_seq * bs, H, D]`` in position order. Entries
    past a sequence's length hold stale pool contents and MUST be
    masked by the consumer (the decode attention masks on length)."""
    N = pages.shape[1]
    tbl = jnp.minimum(block_tables, N - 1)  # clip OOB ids into the pool
    out = pages[layer][tbl]                 # [B, M, bs, H, D]
    B, M, bs, H, D = out.shape
    return out.reshape(B, M * bs, H, D)


def copy_block(cache: KVCache, src, dst) -> KVCache:
    """Duplicate one block's contents across every layer (``new[dst] =
    old[src]``) — the device half of copy-on-write: when a sequence
    would append into a block shared with another sequence, the
    scheduler allocates a private block, copies the shared contents
    here, and rewrites its table entry. ``src``/``dst`` may be traced
    int32 scalars so a single jitted program serves every copy."""
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    out = KVCache(
        k=cache.k.at[:, dst].set(cache.k[:, src]),
        v=cache.v.at[:, dst].set(cache.v[:, src]),
    )
    if cache.k_scale is not None:
        # quantized pools: the copy must carry the source block's
        # scales, or the CoW'd block would dequantize the right bytes
        # with the wrong (stale/zero) scales — silently wrong K/V
        out = out._replace(
            k_scale=cache.k_scale.at[:, dst].set(cache.k_scale[:, src]),
            v_scale=cache.v_scale.at[:, dst].set(cache.v_scale[:, src]))
    return out


def gather_blocks(cache: KVCache, perm: jax.Array) -> KVCache:
    """Apply a block permutation to the pool (``new[i] = old[perm[i]]``)
    — the device half of :func:`defragment`. Scale pools (quantized
    storage) permute with their payload."""
    out = KVCache(k=cache.k[:, perm], v=cache.v[:, perm])
    if cache.k_scale is not None:
        out = out._replace(k_scale=cache.k_scale[:, perm],
                           v_scale=cache.v_scale[:, perm])
    return out


def defragment(cache: KVCache, allocator: BlockAllocator,
               host_tables: np.ndarray):
    """Compact live blocks to the low pool indices.

    Long-running continuous batching interleaves allocations from many
    sequences, so frees leave the pool checkerboarded; compaction
    restores a contiguous free region (and, on hardware with block-
    granular paging tricks, locality). Returns ``(new_cache,
    new_host_tables)`` and rewrites the allocator's free list,
    refcounts, and prefix index in the compacted id space. Refcount-0
    cached blocks are dropped (they appear in no table, so compaction
    cannot preserve them) — an acceptable trade for a maintenance op.
    The device shuffle is one gather over the pool — call it rarely,
    from a maintenance point, never inside the per-step loop.
    """
    tables = np.array(host_tables, np.int32, copy=True)
    live = np.unique(tables[tables >= 0])
    live_set = {int(x) for x in live}
    missing = [b for b in allocator._ref if b not in live_set]
    if missing:
        raise ValueError(
            f"defragment: blocks {sorted(missing)} hold references but "
            "appear in no table — allocator and tables are inconsistent")
    mapping = {int(old): new for new, old in enumerate(live)}
    perm = np.arange(cache.num_blocks, dtype=np.int32)
    perm[: len(live)] = live
    # the remaining slots get the displaced (dead) blocks, keeping perm
    # a true permutation so no block id aliases another
    dead = np.setdiff1d(np.arange(cache.num_blocks, dtype=np.int32), live,
                        assume_unique=False)
    perm[len(live):] = dead
    for idx, old in np.ndenumerate(tables):
        if old >= 0:
            tables[idx] = mapping[int(old)]
    # rebuild allocator state in the compacted id space: cached blocks
    # are evicted, live blocks keep their refcounts and index entries
    for b in allocator._evictable:       # dropped, charged as evictions
        owner = allocator._cached_owner.pop(b, None)
        if owner is not None:
            allocator._evicted_by_tenant[owner] = \
                allocator._evicted_by_tenant.get(owner, 0) + 1
    allocator.num_evictions += len(allocator._evictable)
    allocator._evictable.clear()
    allocator._ref = {mapping[b]: c for b, c in allocator._ref.items()}
    allocator._tenant_refs = {mapping[b]: refs for b, refs in
                              allocator._tenant_refs.items()}
    allocator._hash_to_block = {
        h: mapping[b] for h, b in allocator._hash_to_block.items()
        if b in mapping}
    allocator._block_to_hash = {
        b: h for h, b in allocator._hash_to_block.items()}
    allocator._cached_owner = {
        mapping[b]: t for b, t in allocator._cached_owner.items()
        if b in mapping}
    allocator._free = list(range(cache.num_blocks - 1, len(live) - 1, -1))
    return gather_blocks(cache, jnp.asarray(perm)), tables
