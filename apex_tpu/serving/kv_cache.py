"""Paged KV-cache: fixed-shape block pools + host-side block accounting.

The serving-side analog of vLLM's PagedAttention cache (PAPERS.md) on
XLA's terms: device memory is a fixed pool of ``num_blocks`` blocks per
layer, laid out ``[num_layers, num_blocks, block_size, num_heads,
head_dim]``, and a sequence owns a *block table* — the ordered list of
block ids holding its tokens. Every jitted program sees only fixed
shapes (the pool, a ``[B, max_blocks_per_seq]`` int32 table, and
``[B]`` lengths), so admission, eviction, and sequence growth never
trigger recompilation: the continuous-batching engine swaps table
*values*, not shapes.

Division of labor (the load-bearing design point):

- **Device side** (jit-stable, pure): :func:`paged_write` scatters new
  K/V into blocks, :func:`gather_kv` reads a sequence back out, and
  :func:`gather_blocks` applies a defrag permutation. All take the
  pool + int32 indices; invalid slots are routed to an out-of-bounds
  block id and dropped by the scatter (``mode="drop"``), so inactive
  batch slots cost nothing and write nowhere.
- **Host side** (Python, between steps): :class:`BlockAllocator` is a
  free-list over block ids — allocation, free, utilization — and
  :func:`defragment` compacts live blocks to the low indices (returns
  the gather permutation + rewritten tables). The scheduler consults
  the allocator; the device never sees it.

Storage dtype rides the existing amp policy: :func:`default_kv_dtype`
returns the active ``amp.initialize`` handle's compute dtype (bf16 for
O1-O3, fp32 for O0) unless overridden — the cache is activation-class
state, so it follows the activation precision, not the master-weight
precision.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def default_kv_dtype(dtype=None):
    """Resolve the KV-storage dtype through the amp policy: an explicit
    ``dtype`` wins; otherwise the last ``amp.initialize`` handle's
    compute dtype (bf16 under O1-O3); fp32 when amp was never set up."""
    if dtype is not None:
        return jnp.dtype(dtype)
    from apex_tpu.amp import _amp_state

    handle = _amp_state._amp_state.handle
    if handle is not None:
        return jnp.dtype(handle.properties.compute_dtype)
    return jnp.dtype(jnp.float32)


class KVCache(NamedTuple):
    """The device-side block pools (a pytree of two arrays).

    ``k`` / ``v``: ``[num_layers, num_blocks, block_size, num_heads,
    head_dim]``. The pool is allocated once at engine start and updated
    functionally (scatter in, new pytree out); the layout keeps the
    ``(num_heads * head_dim)`` product in the trailing dims so a block
    row is lane-tileable on TPU.
    """

    k: jax.Array
    v: jax.Array

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def block_size(self) -> int:
        return self.k.shape[2]

    @property
    def num_heads(self) -> int:
        return self.k.shape[3]

    @property
    def head_dim(self) -> int:
        return self.k.shape[4]

    @classmethod
    def create(cls, num_layers: int, num_blocks: int, block_size: int,
               num_heads: int, head_dim: int, dtype=None) -> "KVCache":
        dt = default_kv_dtype(dtype)
        shape = (num_layers, num_blocks, block_size, num_heads, head_dim)
        return cls(k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt))


class CacheOutOfBlocks(RuntimeError):
    """The free list cannot serve an allocation (admission should have
    been throttled, or the pool is fragmented — see :func:`defragment`)."""


class BlockAllocator:
    """Host-side free-list over the pool's block ids.

    Lives entirely outside jit: the scheduler calls ``alloc``/``free``
    between steps and writes the resulting ids into host block tables,
    which are shipped to the device as plain int32 inputs.
    """

    def __init__(self, num_blocks: int):
        self.num_blocks = int(num_blocks)
        # pop() from the end serves ascending ids first — keeps early
        # allocations compact, which makes defrag cheap in the common case
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return self.num_blocks - len(self._free)

    @property
    def utilization(self) -> float:
        """Fraction of pool blocks currently owned by live sequences."""
        return self.num_used / max(self.num_blocks, 1)

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise CacheOutOfBlocks(
                f"requested {n} blocks, {len(self._free)} free of "
                f"{self.num_blocks}")
        return [self._free.pop() for _ in range(n)]

    def free(self, ids: Sequence[int]) -> None:
        for b in ids:
            if not (0 <= b < self.num_blocks):
                raise ValueError(f"block id {b} out of range")
            if b in self._free:
                raise ValueError(f"double free of block {b}")
            self._free.append(b)

    def reset(self) -> None:
        self._free = list(range(self.num_blocks - 1, -1, -1))


def blocks_needed(num_tokens: int, block_size: int) -> int:
    return -(-int(num_tokens) // int(block_size))


def device_block_table(host_tables: np.ndarray, num_blocks: int) -> jax.Array:
    """Host tables use -1 for unallocated entries; the device convention
    is ``num_blocks`` (one past the pool) so scatters drop and gathers
    clip into already-masked positions."""
    t = np.asarray(host_tables, np.int32)
    return jnp.asarray(np.where(t >= 0, t, num_blocks), jnp.int32)


def paged_write(pages: jax.Array, layer: int, block_tables: jax.Array,
                positions: jax.Array, values: jax.Array,
                valid: jax.Array) -> jax.Array:
    """Scatter per-token K or V into one layer's blocks.

    Args:
      pages: the full pool ``[L, N, bs, H, D]``.
      layer: static layer index.
      block_tables: ``[B, max_blocks_per_seq]`` int32 (device
        convention: out-of-bounds id for unallocated entries).
      positions: ``[B, S]`` absolute token positions within each
        sequence.
      values: ``[B, S, H, D]`` the tokens' K or V heads.
      valid: ``[B, S]`` bool; False routes the write out of bounds,
        where ``mode="drop"`` discards it (padding tokens, inactive
        decode slots).
    """
    N, bs = pages.shape[1], pages.shape[2]
    page = jnp.take_along_axis(block_tables, positions // bs, axis=1)
    page = jnp.where(valid, page, N)
    off = positions % bs
    return pages.at[layer, page, off].set(
        values.astype(pages.dtype), mode="drop")


def gather_kv(pages: jax.Array, layer: int,
              block_tables: jax.Array) -> jax.Array:
    """Read every sequence's cached tokens back out of one layer's pool:
    ``[B, max_blocks_per_seq * bs, H, D]`` in position order. Entries
    past a sequence's length hold stale pool contents and MUST be
    masked by the consumer (the decode attention masks on length)."""
    N = pages.shape[1]
    tbl = jnp.minimum(block_tables, N - 1)  # clip OOB ids into the pool
    out = pages[layer][tbl]                 # [B, M, bs, H, D]
    B, M, bs, H, D = out.shape
    return out.reshape(B, M * bs, H, D)


def gather_blocks(cache: KVCache, perm: jax.Array) -> KVCache:
    """Apply a block permutation to the pool (``new[i] = old[perm[i]]``)
    — the device half of :func:`defragment`."""
    return KVCache(k=cache.k[:, perm], v=cache.v[:, perm])


def defragment(cache: KVCache, allocator: BlockAllocator,
               host_tables: np.ndarray):
    """Compact live blocks to the low pool indices.

    Long-running continuous batching interleaves allocations from many
    sequences, so frees leave the pool checkerboarded; compaction
    restores a contiguous free region (and, on hardware with block-
    granular paging tricks, locality). Returns ``(new_cache,
    new_host_tables)`` and rewrites the allocator's free list. The
    device shuffle is one gather over the pool — call it rarely, from
    a maintenance point, never inside the per-step loop.
    """
    tables = np.array(host_tables, np.int32, copy=True)
    live = np.unique(tables[tables >= 0])
    mapping = {int(old): new for new, old in enumerate(live)}
    perm = np.arange(cache.num_blocks, dtype=np.int32)
    perm[: len(live)] = live
    # the remaining slots get the displaced (dead) blocks, keeping perm
    # a true permutation so no block id aliases another
    dead = np.setdiff1d(np.arange(cache.num_blocks, dtype=np.int32), live,
                        assume_unique=False)
    perm[len(live):] = dead
    for idx, old in np.ndenumerate(tables):
        if old >= 0:
            tables[idx] = mapping[int(old)]
    allocator._free = list(range(cache.num_blocks - 1, len(live) - 1, -1))
    return gather_blocks(cache, jnp.asarray(perm)), tables
