"""The framed RPC wire protocol for out-of-process replicas
(docs/fleet.md, "Process replicas").

One frame = an 8-byte big-endian length prefix + the UTF-8 bytes of
one JSON record, SEALED with :func:`~apex_tpu.utils.integrity.
seal_record` before encoding and verified with :func:`verify_record`
after parsing — so a torn, truncated, or rotted frame is an
:class:`~apex_tpu.utils.integrity.IntegrityError` at the reader,
never a silent mis-parse. The module is deliberately minimal and
stdlib-only (``struct``/``select``/``os``/``json`` — no sockets, no
serialization framework): frames ride ordinary pipe file descriptors
(the child's stdin/stdout), and everything protocol-level above a
frame — request ids, method dispatch, retries, at-most-once dedupe —
belongs to :mod:`~apex_tpu.serving.process_replica` and
:mod:`~apex_tpu.serving.replica_worker`.

Failure taxonomy (the reader's contract):

- clean EOF at a frame boundary → :class:`WireClosedError` (the peer
  exited; for a parent this is replica death, for a child it is
  shutdown);
- EOF mid-header or mid-body → ``IntegrityError("wire", "truncated
  ...")`` (a torn frame: the peer died mid-write, or a chaos plan
  truncated it);
- a body that is not valid JSON → ``IntegrityError("wire", "torn
  frame ...")``;
- a parsed record whose embedded checksum mismatches →
  ``IntegrityError`` from :func:`verify_record` (frame rot);
- a length prefix beyond ``max_bytes`` → ``IntegrityError("wire",
  "oversize frame ...")``, REFUSED before a single body byte is read
  (a corrupt length must not make the reader allocate gigabytes);
- no bytes within ``timeout_s`` → :class:`WireTimeoutError` (an
  unresponsive peer — the parent's per-call timeout).

Numpy arrays (KV payloads riding ``export_prefix_payloads`` /
``import_prefix_payloads``) do not fit JSON: callers encode them with
:func:`encode_arrays` (base64 + dtype + shape markers) BEFORE the
frame is sealed and decode with :func:`decode_arrays` after it
verifies, so the checksum covers exactly the bytes on the wire.
"""

from __future__ import annotations

import base64
import json
import os
import select
import struct
from typing import Dict, Optional

from apex_tpu.utils.integrity import (
    IntegrityError,
    seal_record,
    verify_record,
)

# the one sealed-record site name every frame verifies under
WIRE_SITE = "wire"
# 8-byte big-endian unsigned length prefix
_HEADER = struct.Struct(">Q")
HEADER_BYTES = _HEADER.size
# the oversize-refusal bound: far above any real frame (a tiny-model
# KV payload is kilobytes; a checkpoint is bounded by the queue), far
# below anything a corrupt length prefix could use to OOM the reader
MAX_FRAME_BYTES = 64 << 20

_ARRAY_KEY = "__ndarray__"


class WireClosedError(RuntimeError):
    """The peer closed the pipe at a clean frame boundary — process
    exit, not corruption. A parent treats this as replica death; a
    child treats it as shutdown."""


class WireTimeoutError(RuntimeError):
    """No (complete) frame arrived within the reader's timeout — the
    peer is alive-but-unresponsive, the failure mode a parent must
    bound (docs/fleet.md, RPC timeout/retry policy)."""


def encode_frame(record: Dict, max_bytes: int = MAX_FRAME_BYTES) -> bytes:
    """Seal ``record`` (in place, like every sealed artifact) and
    encode it as one length-prefixed frame. Refuses — with
    ``IntegrityError`` — to build a frame past ``max_bytes``: the
    writer's half of the oversize contract, so a runaway payload fails
    loudly at the sender instead of being refused at the reader."""
    body = json.dumps(seal_record(record),
                      separators=(",", ":")).encode("utf-8")
    if len(body) > max_bytes:
        raise IntegrityError(
            WIRE_SITE, f"refusing to encode oversize frame: "
                       f"{len(body)} bytes > max {max_bytes}")
    return _HEADER.pack(len(body)) + body


def write_frame(fd: int, record: Dict,
                max_bytes: int = MAX_FRAME_BYTES) -> None:
    """Write one sealed frame to a raw file descriptor. A
    ``BrokenPipeError``/``OSError`` propagates — the peer is gone and
    the caller owns that verdict (``ReplicaUnavailableError`` for a
    parent, exit for a child)."""
    data = encode_frame(record, max_bytes)
    view = memoryview(data)
    while view:
        n = os.write(fd, view)
        view = view[n:]


def _read_exact(fd: int, n: int, timeout_s: Optional[float],
                what: str) -> bytes:
    """Read exactly ``n`` bytes. EOF with ZERO bytes read is the
    caller's to interpret (returned as ``b""`` only when ``what`` is
    the header — a clean close); EOF mid-read is a torn frame."""
    chunks = []
    got = 0
    while got < n:
        if timeout_s is not None:
            ready, _, _ = select.select([fd], [], [], timeout_s)
            if not ready:
                raise WireTimeoutError(
                    f"no {what} bytes within {timeout_s}s "
                    f"({got}/{n} read)")
        chunk = os.read(fd, n - got)
        if not chunk:
            if got == 0 and what == "header":
                raise WireClosedError("peer closed at a frame boundary")
            raise IntegrityError(
                WIRE_SITE, f"truncated {what}: peer closed after "
                           f"{got}/{n} bytes")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(fd: int, timeout_s: Optional[float] = None,
               max_bytes: int = MAX_FRAME_BYTES,
               chaos=None) -> Dict:
    """Read and verify one frame from a raw file descriptor.

    ``chaos`` is the parent-side fault seam (docs/robustness.md): a
    ``bytes -> bytes`` hook applied to the received body BEFORE
    parsing, so a seeded plan can truncate or rot exactly the frame it
    means to — the resulting parse/checksum failure then exercises the
    real retry path. The hook runs after the full frame left the pipe,
    so a simulated truncation never desyncs the stream."""
    header = _read_exact(fd, HEADER_BYTES, timeout_s, "header")
    (length,) = _HEADER.unpack(header)
    if length > max_bytes:
        raise IntegrityError(
            WIRE_SITE, f"oversize frame refused: length prefix "
                       f"{length} bytes > max {max_bytes}")
    body = _read_exact(fd, length, timeout_s, "body")
    if chaos is not None:
        body = chaos(body)
    try:
        record = json.loads(body.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise IntegrityError(
            WIRE_SITE, f"torn frame: body is not valid JSON ({e})")
    if not isinstance(record, dict):
        raise IntegrityError(
            WIRE_SITE, f"torn frame: expected a record object, got "
                       f"{type(record).__name__}")
    verify_record(record, WIRE_SITE)
    return record


def encode_arrays(obj):
    """Recursively replace numpy arrays with JSON-able
    ``{"__ndarray__": {dtype, shape, b64}}`` markers (a NEW tree; the
    input is never mutated). Applied BEFORE sealing, so the frame
    checksum covers the encoded bytes end to end."""
    import numpy as np

    if isinstance(obj, np.ndarray):
        a = np.ascontiguousarray(obj)
        return {_ARRAY_KEY: {
            "dtype": str(a.dtype),
            "shape": [int(s) for s in a.shape],
            "b64": base64.b64encode(a.tobytes()).decode("ascii"),
        }}
    if isinstance(obj, dict):
        return {k: encode_arrays(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [encode_arrays(v) for v in obj]
    return obj


def decode_arrays(obj):
    """Invert :func:`encode_arrays` after the frame verified: markers
    become numpy arrays (bit-identical to the sender's — base64 is
    lossless and dtype/shape ride along)."""
    import numpy as np

    if isinstance(obj, dict):
        if set(obj) == {_ARRAY_KEY}:
            m = obj[_ARRAY_KEY]
            return np.frombuffer(
                base64.b64decode(m["b64"]),
                dtype=np.dtype(m["dtype"])).reshape(m["shape"]).copy()
        return {k: decode_arrays(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [decode_arrays(v) for v in obj]
    return obj
