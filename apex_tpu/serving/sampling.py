"""Token sampling for the decode loop: greedy / temperature / top-k /
top-p, fully vectorized and jit-stable.

Every knob is a *traced* per-slot array (``[B]``), never a static
argument: the continuous-batching engine serves requests with different
sampling settings from the same compiled decode program, so a request's
temperature must be data, not a trace constant. The whole sampler is
branch-free — greedy is the ``temperature <= 0`` lane of a ``where``,
top-k and top-p are masks over the descending-sorted logits — and runs
inside the engine's two jitted programs (a separately-jitted sampler
would be a third compilation, breaking the two-program contract
documented in docs/serving.md).

Two entry points share one filtering chain:

- :func:`sample_tokens` — one PRNG key for the whole batch. A row's
  draw still depends on its ROW INDEX (the key's Gumbel noise is laid
  out per row), so it is only reproducible while batch composition is
  fixed — fine for standalone use and the prefill path (``B == 1``).
- :func:`sample_tokens_per_lane` — one PRNG key PER ROW. A row's draw
  depends only on its own key and logits, never on which lane it
  occupies or what else shares the batch. The engine keys each lane by
  ``fold_in(request_key, token_index)``, which is what makes generation
  bit-for-bit identical across ``decode_steps`` settings, lane
  placements, and preemption/resume schedules (docs/serving.md).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling configuration.

    ``temperature <= 0`` selects greedy decoding (argmax); ``top_k <= 0``
    disables the top-k filter; ``top_p >= 1`` disables nucleus
    filtering. Filters compose: top-k first, then top-p over what
    survives, matching the common serving convention.

    ``top_k`` values at or above the vocabulary size are equivalent to
    ``top_k = 0`` (disabled): the filter keeps the ``top_k``
    best-ranked tokens, and every token ranks inside ``top_k >= V``.
    ``validate()`` cannot clamp this — the vocabulary size is a model
    property the params object never sees — so the equivalence is the
    contract instead (regression-tested in tests/test_serving.py).
    """

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0

    def validate(self) -> "SamplingParams":
        if self.top_p <= 0.0 or self.top_p > 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        return self


def _filtered_sorted_logits(logits, temperature, top_k, top_p):
    """The shared filtering chain: temperature-scale, sort descending,
    mask by top-k rank and top-p mass. Returns ``(filtered, order,
    greedy)`` where ``filtered`` are the sorted scaled logits with
    killed positions at ``-inf``, ``order`` maps sorted rank back to
    vocabulary id, and ``greedy`` is the plain argmax per row."""
    lg = logits.astype(jnp.float32)
    V = lg.shape[-1]
    greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)

    safe_t = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = lg / safe_t

    # descending sort once; both filters are rank/mass masks over it
    order = jnp.argsort(-scaled, axis=-1)               # [B, V]
    sorted_lg = jnp.take_along_axis(scaled, order, axis=-1)
    rank = jax.lax.broadcasted_iota(jnp.int32, sorted_lg.shape, 1)
    # top_k >= V keeps every rank — the documented "disabled" alias
    k_eff = jnp.where(top_k > 0, top_k, V)[:, None]
    keep_k = rank < k_eff
    # nucleus mass is measured over the RENORMALIZED top-k survivors
    # (the HF warper-chain composition the docstring promises), not the
    # full-vocabulary distribution — otherwise combining the two knobs
    # keeps systematically more tail tokens than configured
    probs = jax.nn.softmax(jnp.where(keep_k, sorted_lg, -jnp.inf), axis=-1)
    # exclusive cumulative mass: a token stays while the mass BEFORE it
    # is under top_p, so the first token always survives
    cum_before = jnp.cumsum(probs, axis=-1) - probs
    keep = keep_k & (cum_before < top_p[:, None])
    return jnp.where(keep, sorted_lg, -jnp.inf), order, greedy


def sample_tokens(logits, key, temperature, top_k, top_p):
    """Draw one token per row from a single shared key.

    Args:
      logits: ``[B, V]`` (any float dtype; filtering runs in fp32).
      key: a single PRNG key; rows draw independent categorical samples.
      temperature: ``[B]`` fp32; ``<= 0`` means greedy for that row.
      top_k: ``[B]`` int32; ``<= 0`` (or ``>= V``) disables.
      top_p: ``[B]`` fp32 nucleus mass; ``>= 1`` disables.

    Returns ``[B]`` int32 token ids.
    """
    filtered, order, greedy = _filtered_sorted_logits(
        logits, temperature, top_k, top_p)
    pos = jax.random.categorical(key, filtered, axis=-1)
    sampled = jnp.take_along_axis(order, pos[:, None], axis=-1)[:, 0]
    return jnp.where(temperature > 0.0, sampled, greedy).astype(jnp.int32)


def sample_tokens_per_lane(logits, keys, temperature, top_k, top_p):
    """Draw one token per row, each row from ITS OWN key.

    Same filtering semantics as :func:`sample_tokens`; the difference is
    reproducibility scope. Row ``i`` draws ``categorical(keys[i],
    filtered[i])`` — no row-index dependence, no cross-row coupling —
    so a sequence keyed by per-request/per-token keys samples the same
    token no matter which batch lane it rides in, how many other lanes
    are live, or how many scan steps the dispatch fuses. This is the
    decode-side sampler of the multi-step fused decode program
    (docs/serving.md).

    Args:
      logits: ``[B, V]``.
      keys: ``[B]`` PRNG keys (a ``[B, 2]`` uint32 array for the
        threefry impl), one per row.
      temperature / top_k / top_p: as in :func:`sample_tokens`.

    Returns ``[B]`` int32 token ids.
    """
    filtered, order, greedy = _filtered_sorted_logits(
        logits, temperature, top_k, top_p)
    pos = jax.vmap(jax.random.categorical)(keys, filtered)
    sampled = jnp.take_along_axis(order, pos[:, None], axis=-1)[:, 0]
    return jnp.where(temperature > 0.0, sampled, greedy).astype(jnp.int32)
