"""Token sampling for the decode loop: greedy / temperature / top-k /
top-p, fully vectorized and jit-stable.

Every knob is a *traced* per-slot array (``[B]``), never a static
argument: the continuous-batching engine serves requests with different
sampling settings from the same compiled decode program, so a request's
temperature must be data, not a trace constant. The whole sampler is
branch-free — greedy is the ``temperature <= 0`` lane of a ``where``,
top-k and top-p are masks over the descending-sorted logits — and runs
inside the engine's two jitted programs (a separately-jitted sampler
would be a third compilation, breaking the two-program contract
documented in docs/serving.md).

Three entry points share one filtering chain:

- :func:`sample_tokens` — one PRNG key for the whole batch. A row's
  draw still depends on its ROW INDEX (the key's Gumbel noise is laid
  out per row), so it is only reproducible while batch composition is
  fixed — fine for standalone use and the prefill path (``B == 1``).
- :func:`sample_tokens_per_lane` — one PRNG key PER ROW. A row's draw
  depends only on its own key and logits, never on which lane it
  occupies or what else shares the batch. The engine keys each lane by
  ``fold_in(request_key, token_index)``, which is what makes generation
  bit-for-bit identical across ``decode_steps`` settings, lane
  placements, and preemption/resume schedules (docs/serving.md).
- :func:`spec_verify_tokens` — the speculative-decoding accept rule
  (Leviathan et al.): given target logits for every candidate position
  of a drafted span, decide per lane how many draft tokens the target
  distribution accepts and sample the first-rejection correction (or
  the all-accepted bonus) token. Greedy lanes use the exact argmax
  equality test, so greedy speculative output is bit-identical to
  non-speculative greedy whenever the verify and decode programs
  agree on argmaxes (certified per backend — see the function
  docstring); sampled lanes use the
  rejection rule for a deterministic drafter (accept ``d`` with
  probability ``p(d)`` under the filtered target distribution, resample
  the rejection from ``p`` with ``d`` removed), which preserves the
  output distribution exactly.

Both batch entry points short-circuit to a plain ``argmax`` via
``jax.lax.cond`` when NO row samples (``temperature <= 0``
everywhere): the predicate is traced, so one compiled program serves
both regimes, but an all-greedy batch skips the sort/filter/softmax
chain at run time — a micro-win paid on every decode iteration and
every speculative verify step.

The sampler is deliberately MESH-OBLIVIOUS (docs/serving.md, "Mesh
sharding"): by the time logits reach it they are replicated — the
model's row-parallel projections all-reduced the last sharded
contraction — and every op here (argmax, the descending sort, the
rank/mass masks, the categorical draws) reduces over the UNSHARDED
vocabulary axis with per-lane keys, so the engine's sharded programs
sample bit-identically to the single-device ones at any mesh shape
and sampling adds zero collectives of its own.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling configuration.

    ``temperature <= 0`` selects greedy decoding (argmax); ``top_k <= 0``
    disables the top-k filter; ``top_p >= 1`` disables nucleus
    filtering. Filters compose: top-k first, then top-p over what
    survives, matching the common serving convention.

    ``top_k`` values at or above the vocabulary size are equivalent to
    ``top_k = 0`` (disabled): the filter keeps the ``top_k``
    best-ranked tokens, and every token ranks inside ``top_k >= V``.
    ``validate()`` cannot clamp this — the vocabulary size is a model
    property the params object never sees — so the equivalence is the
    contract instead (regression-tested in tests/test_serving.py).
    """

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0

    def validate(self) -> "SamplingParams":
        if self.top_p <= 0.0 or self.top_p > 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        return self


def _filtered_sorted_logits(logits, temperature, top_k, top_p):
    """The shared filtering chain: temperature-scale, sort descending,
    mask by top-k rank and top-p mass. Returns ``(filtered, order,
    greedy)`` where ``filtered`` are the sorted scaled logits with
    killed positions at ``-inf``, ``order`` maps sorted rank back to
    vocabulary id, and ``greedy`` is the plain argmax per row."""
    lg = logits.astype(jnp.float32)
    V = lg.shape[-1]
    greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)

    safe_t = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = lg / safe_t

    # descending sort once; both filters are rank/mass masks over it
    order = jnp.argsort(-scaled, axis=-1)               # [B, V]
    sorted_lg = jnp.take_along_axis(scaled, order, axis=-1)
    rank = jax.lax.broadcasted_iota(jnp.int32, sorted_lg.shape, 1)
    # top_k >= V keeps every rank — the documented "disabled" alias
    k_eff = jnp.where(top_k > 0, top_k, V)[:, None]
    keep_k = rank < k_eff
    # nucleus mass is measured over the RENORMALIZED top-k survivors
    # (the HF warper-chain composition the docstring promises), not the
    # full-vocabulary distribution — otherwise combining the two knobs
    # keeps systematically more tail tokens than configured
    probs = jax.nn.softmax(jnp.where(keep_k, sorted_lg, -jnp.inf), axis=-1)
    # exclusive cumulative mass: a token stays while the mass BEFORE it
    # is under top_p, so the first token always survives
    cum_before = jnp.cumsum(probs, axis=-1) - probs
    keep = keep_k & (cum_before < top_p[:, None])
    return jnp.where(keep, sorted_lg, -jnp.inf), order, greedy


def sample_tokens(logits, key, temperature, top_k, top_p):
    """Draw one token per row from a single shared key.

    Args:
      logits: ``[B, V]`` (any float dtype; filtering runs in fp32).
      key: a single PRNG key; rows draw independent categorical samples.
      temperature: ``[B]`` fp32; ``<= 0`` means greedy for that row.
      top_k: ``[B]`` int32; ``<= 0`` (or ``>= V``) disables.
      top_p: ``[B]`` fp32 nucleus mass; ``>= 1`` disables.

    Returns ``[B]`` int32 token ids.
    """
    greedy = jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)

    def _sampled(_):
        filtered, order, _ = _filtered_sorted_logits(
            logits, temperature, top_k, top_p)
        pos = jax.random.categorical(key, filtered, axis=-1)
        sampled = jnp.take_along_axis(order, pos[:, None], axis=-1)[:, 0]
        return jnp.where(temperature > 0.0, sampled, greedy).astype(jnp.int32)

    # all-greedy batches skip the whole sort/filter chain at run time;
    # greedy rows of mixed batches still take the argmax lane of the
    # where, so the fast path is bit-identical by construction (tested)
    return jax.lax.cond(jnp.any(temperature > 0.0), _sampled,
                        lambda _: greedy, None)


def sample_tokens_per_lane(logits, keys, temperature, top_k, top_p):
    """Draw one token per row, each row from ITS OWN key.

    Same filtering semantics as :func:`sample_tokens`; the difference is
    reproducibility scope. Row ``i`` draws ``categorical(keys[i],
    filtered[i])`` — no row-index dependence, no cross-row coupling —
    so a sequence keyed by per-request/per-token keys samples the same
    token no matter which batch lane it rides in, how many other lanes
    are live, or how many scan steps the dispatch fuses. This is the
    decode-side sampler of the multi-step fused decode program
    (docs/serving.md).

    Args:
      logits: ``[B, V]``.
      keys: ``[B]`` PRNG keys (a ``[B, 2]`` uint32 array for the
        threefry impl), one per row.
      temperature / top_k / top_p: as in :func:`sample_tokens`.

    Returns ``[B]`` int32 token ids.
    """
    greedy = jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)

    def _sampled(_):
        filtered, order, _ = _filtered_sorted_logits(
            logits, temperature, top_k, top_p)
        pos = jax.vmap(jax.random.categorical)(keys, filtered)
        sampled = jnp.take_along_axis(order, pos[:, None], axis=-1)[:, 0]
        return jnp.where(temperature > 0.0, sampled, greedy).astype(jnp.int32)

    return jax.lax.cond(jnp.any(temperature > 0.0), _sampled,
                        lambda _: greedy, None)


def spec_verify_tokens(logits, drafts, draft_lens, lane_keys, token_idx,
                       temperature, top_k, top_p):
    """The speculative-decoding accept/correct rule, vectorized over
    lanes and candidate positions.

    The target model scored a drafted span in ONE forward: position
    ``p`` of ``logits`` holds the target distribution for the lane's
    token index ``token_idx[:, p]`` given the carried token plus drafts
    ``0..p-1`` (the engine's verify dispatch arranges exactly this).
    Draft ``p`` (``p < S``) claims the token position ``p`` scores:

    - **greedy lanes** (``temperature <= 0``): accept iff the draft
      equals the position's argmax; the correction and bonus tokens are
      the argmax too. Since accepted drafts ARE the argmaxes, the
      emitted sequence is the non-speculative greedy sequence by
      induction — GIVEN that the verify forward and the scan's decode
      body agree on every position's argmax. That agreement is a
      numerical property of two differently-shaped compiled programs
      (the PR 4 scan-vs-standalone drift is the cautionary tale), so
      it is certified empirically per backend: the cross-K/spec
      bit-identity tests on CPU, ``bench_serving_speculative``'s
      in-section assertion wherever the bench runs.
    - **sampled lanes**: accept draft ``d`` with probability ``p(d)``
      under the FILTERED target distribution (the same
      temperature/top-k/top-p chain non-speculative sampling draws
      from); a rejection resamples from ``p`` with ``d`` masked out —
      ``max(p - q, 0)`` renormalized, for a deterministic
      (point-mass) drafter ``q``. With all drafts accepted the bonus
      token is a FULL sample keyed exactly like the non-speculative
      token at that index, so a lane the drafter left empty emits a
      bit-identical token to the non-speculative engine even when
      sampling.

    Per-token randomness is keyed off ``fold_in(lane_key, token_idx)``
    (the engine's schedule-invariant chain): the accept uniform for a
    token index folds ``1`` on top, the rejection resample folds ``2``,
    and the full/bonus sample uses the base key unchanged — three
    independent streams, all invariant to lane placement,
    ``decode_steps``, and preemption/resume.

    Args:
      logits: ``[B, P, V]`` target logits, ``P = S + 1`` candidate
        positions (the carried token plus ``S`` draft slots).
      drafts: ``[B, S]`` int32 proposed tokens (padding arbitrary).
      draft_lens: ``[B]`` int32 valid proposals per lane (``<= S``).
      lane_keys: ``[B]`` per-request PRNG keys (``[B, 2]`` uint32).
      token_idx: ``[B, P]`` int32 generation index each position
        scores (``gen_count + p``).
      temperature / top_k / top_p: ``[B]`` as elsewhere.

    Returns ``(emitted, n_emit)``: ``emitted`` is ``[B, P]`` int32
    whose first ``n_emit[b]`` entries are lane ``b``'s tokens —
    ``n_acc`` accepted drafts then the correction/bonus token
    (``n_emit = n_acc + 1``); entries past ``n_emit`` are meaningless.
    EOS/budget truncation is the caller's job (the engine's stop-mask
    machinery owns it).
    """
    B, P, V = logits.shape
    S = P - 1
    lg = logits.astype(jnp.float32)
    greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)          # [B, P]
    # pad drafts to [B, P]: position S scores only the bonus token, its
    # "draft" is never consulted (n_acc <= draft_lens <= S)
    drafts_pad = jnp.concatenate(
        [drafts.astype(jnp.int32), jnp.zeros((B, 1), jnp.int32)], axis=1)

    def _greedy_only(_):
        return drafts_pad[:, :S] == greedy[:, :S], greedy, greedy

    def _with_sampled(_):
        flat = lg.reshape(B * P, V)
        t = jnp.repeat(temperature, P)
        k = jnp.repeat(top_k, P)
        p_ = jnp.repeat(top_p, P)
        filtered, order, _ = _filtered_sorted_logits(flat, t, k, p_)
        probs = jax.nn.softmax(filtered, axis=-1)       # killed ranks -> 0
        d_flat = drafts_pad.reshape(B * P)
        hit = order == d_flat[:, None]                  # rank of the draft
        p_draft = jnp.sum(jnp.where(hit, probs, 0.0), axis=-1)
        keys = jax.vmap(jax.random.fold_in)(
            jnp.repeat(lane_keys, P, axis=0), token_idx.reshape(-1))
        u = jax.vmap(
            lambda kk: jax.random.uniform(jax.random.fold_in(kk, 1)))(keys)
        accept_s = (u < p_draft).reshape(B, P)[:, :S]
        # rejection residual: the filtered distribution with the draft
        # token removed (max(p - q, 0) renormalized for point-mass q)
        resid = jnp.where(hit, -jnp.inf, filtered)
        pos_r = jax.vmap(lambda kk, l: jax.random.categorical(
            jax.random.fold_in(kk, 2), l))(keys, resid)
        corr_s = jnp.take_along_axis(
            order, pos_r[:, None], axis=-1)[:, 0].reshape(B, P)
        pos_f = jax.vmap(jax.random.categorical)(keys, filtered)
        full_s = jnp.take_along_axis(
            order, pos_f[:, None], axis=-1)[:, 0].reshape(B, P)
        sampled = (temperature > 0.0)[:, None]
        accept = jnp.where(sampled, accept_s,
                           drafts_pad[:, :S] == greedy[:, :S])
        corr = jnp.where(sampled, corr_s, greedy).astype(jnp.int32)
        full = jnp.where(sampled, full_s, greedy).astype(jnp.int32)
        return accept, corr, full

    accept, corr, full = jax.lax.cond(
        jnp.any(temperature > 0.0), _with_sampled, _greedy_only, None)
    valid = (jax.lax.broadcasted_iota(jnp.int32, (B, S), 1)
             < draft_lens[:, None])
    chain = jnp.cumprod((accept & valid).astype(jnp.int32), axis=1)
    n_acc = jnp.sum(chain, axis=1)                      # [B]
    # all valid drafts accepted -> bonus (full sample at position
    # n_acc); otherwise the rejection correction at position n_acc
    bonus = n_acc == draft_lens
    at = n_acc[:, None]
    final = jnp.where(
        bonus,
        jnp.take_along_axis(full, at, axis=1)[:, 0],
        jnp.take_along_axis(corr, at, axis=1)[:, 0])
    ii = jax.lax.broadcasted_iota(jnp.int32, (B, P), 1)
    emitted = jnp.where(ii < at, drafts_pad, final[:, None])
    return emitted.astype(jnp.int32), n_acc + 1
