"""Fleet serving: a crash-tolerant router over N engine replicas.

The millions-of-users story sits one level above a single
:class:`~apex_tpu.serving.InferenceEngine`: one replica's pool bounds
its concurrency, and — until now — one replica's crash lost every
accepted request it held. :class:`FleetRouter` turns N engines
(in-process here; the replica surface it consumes — ``add_request`` /
``step()`` / ``load()`` / ``probe_prefix`` / ``export_requests`` /
``import_requests`` / ``pop_results`` / ``last_checkpoint`` — is a
thin, host-side, JSON-friendly contract deliberately shaped so a
process or RPC boundary can slide between router and replica) into one
serving surface with three properties (docs/fleet.md):

**Prefix-affinity placement.** The engine's prefix index is keyed by
SHA-256 chain hashes of full-block token contents — globally
comparable, so the router can compute a prompt's chain and ask EVERY
replica how many leading blocks it could serve without recompute
(:meth:`InferenceEngine.probe_prefix`: device index + spill tier).
Routing scores that affinity against load — queue depth plus active
lanes, scaled by each replica's service-time EWMAs relative to the
fleet (the estimators each replica already exports) — so a warm cache
wins until it is busy, and a cold replica wins once the warm one
queues. Deterministic: ties break toward the emptier, then
lower-indexed replica, which is what makes the 1-replica fleet
bit-identical to the bare engine (certified: outputs, statuses, AND
schedule counters).

**Crash failover with zero lost accepted requests.** Each replica
refreshes a lightweight checkpoint every ``snapshot_interval_ticks``
(:meth:`InferenceEngine.checkpoint` — no drain, bounded staleness).
The router's health probe declares a replica dead on (a) any exception
escaping its ``step()`` — including an injected
:class:`~apex_tpu.utils.faults.FaultPlan` crash, the chaos bench's
weapon — or (b) ``health_patience`` consecutive no-progress ticks
while it holds work. Failover re-homes everything: results that
reached terminal inside the checkpoint are adopted directly;
checkpointed live entries re-import onto survivors carrying their
emitted tokens and arrival PRNG identity (tokens emitted after the
checkpoint re-derive bit-identically — resume determinism); accepted
requests the checkpoint never saw re-inject fresh from the router's
own copy. Nothing accepted is ever lost — the ``num_lost_requests``
gauge computes the invariant and the chaos bench asserts it at zero.
A request that kills ``max_request_failovers`` replicas in a row is
the router-level quarantine: it terminal-fails instead of cascading
through the fleet.

**Drain-and-migrate.** :meth:`migrate` moves live requests off a hot
or dying replica through the same records
(:meth:`InferenceEngine.export_requests` drains the in-flight decode,
releases blocks, and serializes; the target imports and re-prefills
through its prefix cache — bit-identical resumption under equal
seeds), optionally shipping the prompt's KV payloads through the spill
tier (:meth:`InferenceEngine.export_prefix_payloads` →
``import_prefix_payloads``) so the target re-admits by device upload
instead of recompute.

Tenancy aggregates fleet-wide: ``FleetConfig.tenant_quotas`` enforces
waiting-depth / footprint / token-rate bounds against the SUM across
replicas at the router's door (the cross-replica ledger PR 9
deferred), each replica's own DRR walk and quotas keep running
unchanged inside it, and ``stats()["tenants"]`` merges the per-replica
rows into one ledger.

**SDC detection** (``sdc_check_interval_ticks``, docs/robustness.md
"Data integrity"): the silent failure mode the health probe cannot
see is a replica that computes *wrong tokens* without crashing. The
router periodically replays a sampled completed request on a second
replica under its original arrival identity — equal configs +
arrival-keyed sampling make the streams bit-identical by construction
— and a divergence, arbitrated by a confirmation replay on an
independent third replica when one exists (the side the majority
contradicts is the suspect, owner or verifier alike), retires the
corrupt replica through the failover path with its host state
untrusted (fresh re-injection; a corrupt replica's checkpoint proves
nothing). Failover checkpoints
and migration records carry content checksums verified before use; a
corrupt checkpoint reads as no checkpoint, a corrupt migration import
is refused and the source keeps the request.

Delivery semantics: terminal results are exactly-once
(:meth:`run` / the router's result map dedupe failover re-derivations);
the streaming feed (:meth:`pop_stream_events`) is exactly-once for
TOKENS — the router's per-request delivery watermark suppresses the
tokens a failover re-derivation replays — while a terminal sentinel
can be lost for a request whose verdict was adopted from a dead
replica's checkpoint (the corpse's stream is unreadable), so terminal
truth belongs to :meth:`run`. ``abort`` routes to the owning replica.
"""

from __future__ import annotations

import dataclasses
import math
import time
from collections import deque
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from apex_tpu.serving.engine import (
    EngineConfig,
    InferenceEngine,
    QueueFullError,
    Request,
    RequestResult,
    TenantQuota,
    TenantThrottledError,
)
from apex_tpu.serving.kv_cache import (
    DEFAULT_TENANT,
    SharedPrefixStore,
    blocks_needed,
    seq_block_hashes,
)
from apex_tpu.serving.mesh import build_mesh
from apex_tpu.serving.process_replica import (
    ProcessReplica,
    ReplicaUnavailableError,
    params_checksum,
)
from apex_tpu.utils.integrity import (
    IntegrityError,
    payload_checksum,
    seal_record,
    verify_payload,
    verify_record,
)


# the internal tenant SDC replays run under on the verifier: real
# tenants' quotas/ledgers must never be charged for verification
# traffic (see _launch_replay)
_SDC_TENANT = "__sdc__"


class FleetFailedError(RuntimeError):
    """No replica is alive to serve (or to receive a failover's
    re-homed requests) and ``FleetConfig.respawn`` is off — the fleet
    itself is down. Carries nothing recoverable: recovery at this
    level is the operator's (restart the fleet; accepted-but-unfinished
    requests are in the router's hands, not lost, but nothing can run
    them)."""


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """The router's knobs (docs/fleet.md). Engine-level behavior —
    pool geometry, speculation, overload ladder, per-replica quotas —
    stays on the shared :class:`EngineConfig` every replica is built
    from (equal configs, equal seeds: that equality is what makes
    migration resume bit-identically)."""

    # replicas spawned at construction; each is a full InferenceEngine
    # over the same (model, params, EngineConfig)
    num_replicas: int = 2
    # placement score = affinity_weight * (cached prompt fraction)
    #                 - load_weight * (relative backlog); see _route
    affinity_weight: float = 1.0
    load_weight: float = 1.0
    # consecutive no-progress ticks (replica holds work, step() keeps
    # returning False) before the health probe declares it dead. An
    # exception escaping step() is death immediately.
    health_patience: int = 2
    # spawn a fresh engine into a dead replica's slot at failover (the
    # fresh replica joins the survivors as a re-homing target). Off by
    # default: a crash loop would respawn forever; on, the fleet
    # tolerates any number of sequential replica deaths.
    respawn: bool = False
    # router-level poison quarantine: a request whose replica dies
    # this many times is terminal-failed ("failed", tokens kept)
    # instead of re-injected — one poison request must not cascade
    # through every replica.
    max_request_failovers: int = 2
    # ship the prompt's KV payloads through the spill tier at
    # migration (export_prefix_payloads -> import_prefix_payloads), so
    # the target re-admits by upload instead of recompute. Needs a
    # spill tier (EngineConfig.spill_max_bytes) on both ends; silently
    # skipped otherwise — transport is an optimization, never a
    # dependency.
    migrate_spill_payloads: bool = True
    # FLEET-WIDE tenant quotas, enforced at the router's door against
    # aggregates across replicas (waiting depth summed, resident
    # charge summed, token rate from the router's own estimator).
    # Independent of EngineConfig.tenant_quotas (per-replica bounds).
    tenant_quotas: Optional[Mapping[str, TenantQuota]] = None
    # time constant of the router's per-tenant token-rate estimator
    # (same math as the engine's: decay exp(-dt/tau), each delivered
    # token adds 1/tau)
    tenant_rate_tau_s: float = 1.0
    # -- fleet SDC detection (docs/fleet.md, docs/robustness.md) -------
    # Every N router ticks, replay one sampled COMPLETED request on a
    # second replica and compare token streams bit-for-bit: equal
    # configs + arrival-keyed sampling make any divergence a defect by
    # construction (a flaky chip, host-RAM rot — the silent failure
    # mode the health probe cannot see), so the diverging request's
    # ORIGINAL owner is marked suspect and retired through the
    # kill/failover path with its host state UNTRUSTED (fresh
    # re-injection — a corrupt replica's checkpoint proves nothing).
    # Replays are eligibility-gated to where bit-identity is certified:
    # greedy requests always, sampled ones only without speculation
    # (speculative span boundaries are schedule-dependent). None = off
    # (the default; the cross-check consumes real verifier capacity).
    sdc_check_interval_ticks: Optional[int] = None
    # -- process replicas (docs/fleet.md, "Process replicas") ----------
    # "in_process" drives InferenceEngine objects in the router's own
    # process (the default, unchanged); "process" runs each replica as
    # a child OS process behind ProcessReplica — same surface, real
    # isolation, real SIGKILL. Process mode requires FleetRouter's
    # ``model_spec`` (the child rebuilds the weights from it and the
    # boot handshake proves they match).
    replica_mode: str = "in_process"
    # per-RPC response deadline for process replicas; an overrun marks
    # the child unresponsive and drives the normal failover path
    # (generous by default: a child's FIRST step compiles the engine
    # programs)
    rpc_timeout_s: float = 300.0
    # resends of one RPC (same id — the worker dedupes) after a torn/
    # rotted response frame, before the replica is declared dead
    rpc_retries: int = 2
    # -- elastic autoscaling (docs/fleet.md, "Autoscaler") -------------
    # the control signal is mean queue depth per alive replica, read
    # each router tick. Above the high watermark for
    # ``autoscale_patience`` CONSECUTIVE ticks -> spawn one replica
    # (prefix-cache warmed from the survivors); below the low
    # watermark as long -> retire one via drain_replica(retire=True).
    # None disables the corresponding direction (both None: no
    # autoscaler at all — certified bit-identical to never setting
    # them). Hysteresis = the patience debounce + the watermark gap
    # (validated: high > low) + min/max bounds.
    autoscale_high_watermark: Optional[float] = None
    autoscale_low_watermark: Optional[float] = None
    autoscale_patience: int = 3
    autoscale_min_replicas: int = 1
    autoscale_max_replicas: Optional[int] = None
    # -- disaggregated prefill/decode roles (docs/fleet.md,
    # "Disaggregated roles") ------------------------------------------
    # None (the default): every replica is colocated ("mixed" — runs
    # prefill AND decode, exactly today's fleet, certified
    # bit-identical). A sequence of "prefill"/"decode", one per
    # replica (at least one of each), splits the fleet into
    # specialists: new prompts place onto prefill replicas by queue
    # depth, a prefill replica's started requests hand off each tick
    # to a decode replica through the checksummed migration transport
    # (KV payloads ride the spill tier — the decode side re-admits as
    # a prefix hit instead of recomputing), and decode placement
    # ranks decode replicas only (affinity + load; prefill
    # specialists are never probed). Roles are PLACEMENT policy, not
    # capability: failover falls back to any survivor when a role
    # group empties, preserving the zero-lost contract. Requires
    # EngineConfig.enable_prefix_caching (the handoff's transport and
    # the decode side's prefix-hit admit are both keyed by the chain
    # hashes); a spill tier (spill_max_bytes) makes the handoff carry
    # KV instead of recomputing, and is strongly recommended.
    replica_roles: Optional[Sequence[str]] = None
    # -- fleet-global shared prefix tier (docs/fleet.md, "Shared
    # prefix tier") ----------------------------------------------------
    # byte budget of the router-owned SharedPrefixStore: ONE shared,
    # deduped, checksummed KV tier across all replicas, fed by replica
    # spill evictions and finished-prefill handoffs and probed at
    # placement — a prefix prefilled on any replica is warm
    # fleet-wide, so an affinity-blind route still lands warm. None
    # (the default): no shared tier, certified bit-identical to the
    # tier-less fleet. Requires EngineConfig.enable_prefix_caching
    # (entries are content-addressed by the chain hashes); replicas
    # need a local spill tier (EngineConfig.spill_max_bytes) to
    # receive seeds — without one a shared hit silently degrades to
    # recompute (the tier is an optimization, never a dependency).
    shared_prefix_bytes: Optional[int] = None
    # scrub coverage: shared-tier entries re-verified against their
    # put-time checksums each router tick, round-robin from where the
    # last pass stopped (the engine spill scrubber's discipline,
    # walked by the router). 0 disables the shared scrub.
    shared_scrub_blocks: int = 8

    def __post_init__(self):
        if self.num_replicas < 1:
            raise ValueError(
                f"num_replicas must be >= 1, got {self.num_replicas}")
        for name in ("affinity_weight", "load_weight"):
            if getattr(self, name) < 0:
                raise ValueError(
                    f"{name} must be >= 0, got {getattr(self, name)}")
        if self.health_patience < 1:
            raise ValueError(
                f"health_patience must be >= 1, got "
                f"{self.health_patience}")
        if self.max_request_failovers < 1:
            raise ValueError(
                f"max_request_failovers must be >= 1, got "
                f"{self.max_request_failovers}")
        if self.tenant_quotas is not None:
            for t, q in self.tenant_quotas.items():
                if not isinstance(q, TenantQuota):
                    raise ValueError(
                        f"tenant_quotas[{t!r}] must be a TenantQuota, "
                        f"got {type(q).__name__}")
                q.validate(t)
        if self.tenant_rate_tau_s <= 0:
            raise ValueError(
                f"tenant_rate_tau_s must be > 0, got "
                f"{self.tenant_rate_tau_s}")
        if (self.sdc_check_interval_ticks is not None
                and self.sdc_check_interval_ticks < 1):
            raise ValueError(
                f"sdc_check_interval_ticks must be >= 1 (or None for "
                f"no cross-checking), got "
                f"{self.sdc_check_interval_ticks}")
        if self.replica_mode not in ("in_process", "process"):
            raise ValueError(
                f"replica_mode must be 'in_process' or 'process', got "
                f"{self.replica_mode!r}")
        if self.rpc_timeout_s <= 0:
            raise ValueError(
                f"rpc_timeout_s must be > 0, got {self.rpc_timeout_s}")
        if self.rpc_retries < 0:
            raise ValueError(
                f"rpc_retries must be >= 0, got {self.rpc_retries}")
        hi, lo = (self.autoscale_high_watermark,
                  self.autoscale_low_watermark)
        if hi is not None and lo is not None and not hi > lo:
            raise ValueError(
                f"autoscale_high_watermark ({hi}) must be strictly "
                f"above autoscale_low_watermark ({lo}) — the gap is "
                "half the anti-flap hysteresis")
        if self.autoscale_patience < 1:
            raise ValueError(
                f"autoscale_patience must be >= 1, got "
                f"{self.autoscale_patience}")
        if self.autoscale_min_replicas < 1:
            raise ValueError(
                f"autoscale_min_replicas must be >= 1, got "
                f"{self.autoscale_min_replicas}")
        if (self.autoscale_max_replicas is not None
                and self.autoscale_max_replicas
                < self.autoscale_min_replicas):
            raise ValueError(
                f"autoscale_max_replicas "
                f"({self.autoscale_max_replicas}) must be >= "
                f"autoscale_min_replicas "
                f"({self.autoscale_min_replicas})")
        if self.replica_roles is not None:
            roles = tuple(self.replica_roles)
            object.__setattr__(self, "replica_roles", roles)
            if len(roles) != self.num_replicas:
                raise ValueError(
                    f"replica_roles must list one role per replica "
                    f"({self.num_replicas}), got {len(roles)}")
            bad = [r for r in roles if r not in ("prefill", "decode")]
            if bad:
                raise ValueError(
                    f"replica_roles entries must be 'prefill' or "
                    f"'decode', got {bad[0]!r}")
            for need in ("prefill", "decode"):
                if need not in roles:
                    raise ValueError(
                        f"replica_roles needs at least one {need!r} "
                        "replica: a disaggregated fleet without one "
                        "can accept work it can never finish")
        if (self.shared_prefix_bytes is not None
                and self.shared_prefix_bytes < 1):
            raise ValueError(
                f"shared_prefix_bytes must be >= 1 (or None for no "
                f"shared tier), got {self.shared_prefix_bytes}")
        if self.shared_scrub_blocks < 0:
            raise ValueError(
                f"shared_scrub_blocks must be >= 0, got "
                f"{self.shared_scrub_blocks}")


@dataclasses.dataclass
class _Replica:
    """One replica slot: the engine plus the router's health view.
    ``mode`` is recorded at spawn so a dead slot (engine dropped)
    still reports what it was."""

    engine: Optional[InferenceEngine]
    alive: bool = True
    stall_streak: int = 0
    routed: int = 0
    error: Optional[str] = None
    mode: str = "in_process"
    # "mixed" (colocated, the default), or the specialist role from
    # FleetConfig.replica_roles; a respawn into the slot keeps it
    role: str = "mixed"


class FleetRouter:
    """Drive N :class:`InferenceEngine` replicas as one serving
    surface. Usage mirrors the engine::

        fleet = FleetRouter(model, params, EngineConfig(...),
                            FleetConfig(num_replicas=3))
        fleet.add_request(Request("a", prompt))
        results = fleet.run(return_status=True)

    ``drafters`` / ``faults`` are optional per-replica lists (chaos
    plans are per-replica by design: killing replica 1 must not fault
    replica 0); ``clock`` is the shared injectable clock; ``obs`` an
    optional :class:`~apex_tpu.observability.Observability` whose
    flight recorder receives the router's ``replica_down`` /
    ``failover`` / ``migrate`` events (replica engines take their own
    observers, not this one)."""

    def __init__(self, model, params, engine_config: EngineConfig,
                 fleet_config: Optional[FleetConfig] = None, *,
                 drafters: Optional[Sequence] = None,
                 faults: Optional[Sequence] = None,
                 clock=None, obs=None,
                 model_spec: Optional[Dict] = None,
                 child_clock: Optional[Dict] = None):
        self.model = model
        self.params = params
        self.engine_config = engine_config
        self.config = fleet_config if fleet_config is not None \
            else FleetConfig()
        self._clock = time.monotonic if clock is None else clock
        self._obs = obs
        if obs is not None:
            obs.use_clock(self._clock)
        n = self.config.num_replicas
        for name, xs in (("drafters", drafters), ("faults", faults)):
            if xs is not None and len(xs) != n:
                raise ValueError(
                    f"{name} must list one entry per replica "
                    f"({n}), got {len(xs)}")
        self._drafters = (list(drafters) if drafters is not None
                          else [None] * n)
        self._faults = (list(faults) if faults is not None
                        else [None] * n)
        # -- process-mode wiring (docs/fleet.md, "Process replicas") ----
        # model_spec: how a child rebuilds (model, params); the router
        # still holds its own copies (placement hashing, SDC replay
        # verification, the respawn checksum handshake all read them).
        # child_clock: the CHILD engines' clock spec — a parent lambda
        # cannot cross a process boundary, so a custom router clock
        # must state what the children run on.
        self._model_spec = model_spec
        self._child_clock = child_clock
        self._params_checksum: Optional[str] = None
        if self.config.replica_mode == "process":
            if model_spec is None:
                raise ValueError(
                    "replica_mode='process' requires model_spec (see "
                    "serving.process_replica.gpt_model_spec): the "
                    "child must be able to rebuild the weights")
            if any(d is not None for d in self._drafters):
                raise ValueError(
                    "custom drafter objects cannot cross the process "
                    "boundary; children build the default NgramDrafter "
                    "from EngineConfig.spec_tokens")
            if clock is not None and child_clock is None:
                raise ValueError(
                    "replica_mode='process' with a custom clock needs "
                    "child_clock (e.g. {'kind': 'constant', 't': 0.0})"
                    " — the children cannot inherit a parent lambda")
            # covers the representation the replicas will SERVE: with
            # weight_quantization set, the child quantizes its
            # spec-rebuilt fp params the same deterministic way before
            # hashing, so a mode mismatch is refused at hello
            self._params_checksum = params_checksum(
                params,
                weight_quantization=engine_config.weight_quantization)
        else:
            if child_clock is not None:
                raise ValueError(
                    "child_clock is only meaningful with "
                    "replica_mode='process'")
            for plan in self._faults:
                if any(s.site == "wire"
                       for s in getattr(plan, "specs", ()) or ()):
                    raise ValueError(
                        "'wire' fault sites need "
                        "replica_mode='process': an in-process "
                        "replica has no frame path to attack")
        # ONE GSPMD mesh, threaded through every replica (and every
        # respawn): replicas of a mesh-sharded engine are mesh-sharded
        # replicas (docs/serving.md "Mesh sharding") — equal mesh +
        # equal config is what keeps migration/failover records
        # replayable bit-identically across them, and the in-process
        # fleet deliberately SHARES the device set (a multi-process
        # deployment gives each replica its own slice; the router's
        # replica surface is already process-separable). All the
        # router's own machinery — placement, checkpoints, migration,
        # SDC cross-checks — is host-side and mesh-agnostic.
        self.mesh = build_mesh(engine_config.mesh_shape)
        # -- disaggregated roles (docs/fleet.md, "Disaggregated
        # roles"): the per-slot role assignment, parallel to
        # self.replicas (autoscaled slots append; respawns keep the
        # slot's role). Colocated fleets run every slot as "mixed".
        self._roles_enabled = self.config.replica_roles is not None
        if self._roles_enabled and not engine_config.enable_prefix_caching:
            raise ValueError(
                "replica_roles requires "
                "EngineConfig.enable_prefix_caching: the prefill->"
                "decode handoff transports KV through the chain-hash-"
                "keyed prefix index, and the decode side admits the "
                "handoff as a prefix hit")
        self._roles: List[str] = (list(self.config.replica_roles)
                                  if self._roles_enabled
                                  else ["mixed"] * n)
        if (self.config.shared_prefix_bytes is not None
                and not engine_config.enable_prefix_caching):
            raise ValueError(
                "shared_prefix_bytes requires "
                "EngineConfig.enable_prefix_caching: the shared tier "
                "is content-addressed by the prefix chain hashes")
        self.replicas: List[_Replica] = [self._spawn(i)
                                         for i in range(n)]
        # fleet-wide request tracking: owner replica per live uid, the
        # router's own Request copy (the failover re-injection source
        # for accepts the checkpoint never saw), terminal results, and
        # the per-uid failover tally backing the poison quarantine
        self._owner: Dict[str, int] = {}
        self._requests: Dict[str, Request] = {}
        self._results: Dict[str, List[int]] = {}
        self._statuses: Dict[str, str] = {}
        self._refails: Dict[str, int] = {}
        self._stream: List[Tuple[str, int, bool]] = []
        # the delivery watermark: per live uid, the tokens the router
        # has already delivered (also the failover re-injection
        # history for accepts no checkpoint saw) and the owning
        # engine's emission cursor — a re-homed request re-deriving
        # tokens the dead replica already streamed resumes BELOW the
        # watermark, and those replays are suppressed (the stream
        # feed stays exactly-once for tokens) and never re-counted by
        # the tenant rate estimator
        self._delivered: Dict[str, List[int]] = {}
        self._emit_pos: Dict[str, int] = {}
        # the fleet-wide tenant rate estimator + the router-door tally
        self._tenant_rate: Dict[str, float] = {}
        self._tenant_rate_t: Dict[str, float] = {}
        self._tenant_status: Dict[str, Dict[str, int]] = {}
        self._num_ticks = 0
        self._num_accepted = 0
        self._num_terminal = 0
        self._num_routed = 0
        self._num_affinity_hits = 0
        self._num_failovers = 0
        self._num_replicas_down = 0
        self._num_respawns = 0
        self._num_migrations = 0
        self._num_migrated_requests = 0
        self._num_reinjected_requests = 0
        self._num_duplicate_results = 0
        self._num_router_failed = 0
        self._num_rejected_queue_full = 0
        self._num_throttled = 0
        # -- data integrity (docs/robustness.md, "Data integrity") -----
        # checkpoints the failover verification refused, migration/
        # failover imports a target refused on a checksum mismatch,
        # and the SDC cross-check's bookkeeping: per-live-uid arrival
        # identity (the replay key), a bounded queue of completed
        # requests awaiting a cross-check, and the in-flight replays
        # keyed by their private "__sdc__N" uids
        self._num_corrupt_checkpoints = 0
        self._num_refused_imports = 0
        self._num_sdc_checks = 0
        self._num_sdc_suspects = 0
        # -- process replicas + autoscaler ------------------------------
        self._num_spawned = 0
        self._num_retired = 0
        self._num_rpc_retries = 0
        self._num_rpc_timeouts = 0
        self._autoscale_hi_streak = 0
        self._autoscale_lo_streak = 0
        # per-role watermark streaks (colocated fleets have the single
        # role "mixed", which mirrors into the scalar streaks above —
        # the signal and behavior reduce exactly to the pre-role
        # autoscaler)
        self._as_hi_streaks: Dict[str, int] = {}
        self._as_lo_streaks: Dict[str, int] = {}
        # -- disaggregation counters (docs/fleet.md) --------------------
        self._num_handoffs = 0
        self._num_handoff_requests = 0
        self._num_handoff_bytes = 0
        self._num_affinity_probes_skipped = 0
        # -- fleet-global shared prefix tier (docs/fleet.md, "Shared
        # prefix tier"): the router-owned store, the per-slot ledger of
        # hashes each replica already published (publish-once per
        # slot: refcounts mean "distinct slots holding these bytes",
        # and the eviction sweep must not re-count a resident entry
        # every tick), and the flow counters. The hash-walk counter is
        # unconditional: it pins the placement hot path's one-walk
        # bound whether or not the tier is on.
        self._shared: Optional[SharedPrefixStore] = None
        self._published: List[set] = [set() for _ in range(n)]
        self._num_shared_publishes = 0
        self._num_shared_hits = 0
        self._num_shared_scrub_blocks_verified = 0
        self._num_hash_walks = 0
        if self.config.shared_prefix_bytes is not None:
            self._shared = SharedPrefixStore(
                self.config.shared_prefix_bytes,
                verify=engine_config.verify_artifacts,
                on_corrupt=self._note_shared_corrupt)
        self._sdc_enabled = \
            self.config.sdc_check_interval_ticks is not None
        self._sdc_arrivals: Dict[str, int] = {}
        self._sdc_queue: deque = deque(maxlen=32)
        self._sdc_pending: Dict[str, Dict] = {}
        self._sdc_seq = 0

    def _spawn(self, idx: int) -> _Replica:
        role = self._roles[idx]
        if self.config.replica_mode == "process":
            eng = ProcessReplica(
                self.engine_config, self._model_spec,
                faults=self._faults[idx],
                clock_spec=self._child_clock,
                rpc_timeout_s=self.config.rpc_timeout_s,
                rpc_retries=self.config.rpc_retries,
                expect_params_checksum=self._params_checksum,
                on_retry=self._note_rpc_retry,
                on_timeout=lambda i=idx: self._note_rpc_timeout(i))
            return _Replica(engine=eng, mode="process", role=role)
        return _Replica(engine=InferenceEngine(
            self.model, self.params, self.engine_config,
            drafter=self._drafters[idx], faults=self._faults[idx],
            clock=self._clock, mesh=self.mesh), mode="in_process",
            role=role)

    def _note_rpc_retry(self) -> None:
        self._num_rpc_retries += 1

    def _note_rpc_timeout(self, idx: int) -> None:
        self._num_rpc_timeouts += 1
        if self._obs is not None:
            self._obs.record("rpc_timeout", replica=idx)

    # -- placement ---------------------------------------------------------

    def _alive(self) -> List[Tuple[int, _Replica]]:
        return [(i, r) for i, r in enumerate(self.replicas)
                if r.alive and r.engine is not None]

    def _seq_hashes(self, tokens: Sequence[int]) -> List[str]:
        # counted (stats()["num_hash_walks"]) so the placement hot
        # path's bound — ONE chain-hash walk per placement decision —
        # stays pinned by test instead of regressing silently
        self._num_hash_walks += 1
        return seq_block_hashes(tokens, self.engine_config.block_size)

    def _ranked(self, seq: Sequence[int],
                stage: Optional[str] = None,
                hashes: Optional[List[str]] = None
                ) -> List[Tuple[int, int]]:
        """Alive replicas as ``(index, matched_blocks)``, best placement
        first (docs/fleet.md, placement score)::

            score(r) = affinity_weight * cached_fraction(r)
                     - load_weight    * backlog_norm(r)

        ``cached_fraction`` = tokens the replica's prefix index + spill
        tier could serve without recompute, over the sequence length;
        ``backlog_norm`` = (queue depth + active lanes) scaled by the
        replica's service EWMAs relative to the fleet mean (a slow
        replica's backlog weighs more), over ``max_batch``. Ties break
        toward the smaller backlog, then the lower index —
        deterministic, and exactly "replica 0" for a 1-replica fleet.

        With ``FleetConfig.replica_roles`` set, placement is
        TWO-STAGE (docs/fleet.md, "Disaggregated roles"): stage
        ``"prefill"`` (new prompts, waiting-entry re-homes) ranks the
        prefill specialists by backlog alone — no affinity probes; a
        specialist fleet's prefill side holds no stable prefix set
        worth scoring — and stage ``"decode"`` (handoffs, mid-decode
        re-homes) ranks the decode specialists by the full
        affinity+load score, SKIPPING the probe of every prefill
        specialist (counted in ``stats()["num_affinity_probes_"
        "skipped"]``). A stage whose role group has no alive member
        falls back to ranking every survivor — roles are placement
        policy, not capability, and the zero-lost contract outranks
        specialization. Colocated fleets ignore ``stage`` entirely
        (bit-identical to the single-stage router).

        ``hashes`` is the prompt's precomputed chain (a caller that
        already walked it passes it in; one walk per placement
        decision). With the shared prefix tier on, its coverage folds
        into ``cached_fraction`` — the returned ``matched_blocks``
        stays the replica's LOCAL match (the shared-tier seeding
        starts where the local match ends)."""
        alive = self._alive()
        if not alive:
            raise FleetFailedError(
                "no replica alive to route to (respawn is off)")
        if self._roles_enabled and stage is not None:
            pool = [(i, rep) for i, rep in alive
                    if self.replicas[i].role == stage]
            if pool and stage == "prefill":
                loads = {i: rep.engine.load() for i, rep in pool}
                order = sorted(
                    (ld["queue_depth"] + ld["active_slots"], i)
                    for i, ld in loads.items())
                return [(i, 0) for _, i in order]
            if pool and stage == "decode":
                self._num_affinity_probes_skipped += (len(alive)
                                                      - len(pool))
                alive = pool
            # an empty role group (every specialist of that role is
            # down): degrade to the full-survivor ranking below
        if hashes is None:
            # callers that already walked the chain (migrate's payload
            # export, the shared-tier seeding in add_request) pass it
            # in — one walk per placement decision, never two
            hashes = self._seq_hashes(seq)
        loads = {i: rep.engine.load() for i, rep in alive}
        svc = {i: (ld["ewma_prefill_dispatch_s"]
                   + ld["ewma_decode_dispatch_s"])
               for i, ld in loads.items()}
        seen = [s for s in svc.values() if s > 0]
        mean_svc = (sum(seen) / len(seen)) if seen else 0.0
        bs = self.engine_config.block_size
        scored = []
        for i, rep in alive:
            ld = loads[i]
            matched = rep.engine.probe_prefix(hashes)
            covered = matched
            if self._shared is not None:
                # fold shared-tier coverage into cached_fraction: the
                # tier serves every replica equally, so the affinity
                # term stays honest about what a placement would NOT
                # recompute (an affinity-blind route still lands warm)
                # while load decides among equally-covered replicas
                covered += self._shared.probe(hashes, start=matched)
            affinity = (covered * bs) / max(len(seq), 1)
            backlog = ld["queue_depth"] + ld["active_slots"]
            # a replica with no EWMAs yet (cold, or freshly respawned)
            # weighs its backlog at the neutral 1.0 — NOT 0, which
            # would make its queue invisible to placement and funnel
            # every arrival at it until it jams
            rel = (svc[i] / mean_svc) if (mean_svc > 0
                                          and svc[i] > 0) else 1.0
            load = backlog * rel / max(self.engine_config.max_batch, 1)
            score = (self.config.affinity_weight * affinity
                     - self.config.load_weight * load)
            scored.append((-score, backlog, i, matched))
        scored.sort()
        return [(i, matched) for _, _, i, matched in scored]

    # -- the fleet door ----------------------------------------------------

    def _tenant_rate_now(self, tenant: str) -> float:
        r = self._tenant_rate.get(tenant, 0.0)
        if r == 0.0:
            return 0.0
        dt = max(0.0, self._clock() - self._tenant_rate_t[tenant])
        return r * math.exp(-dt / self.config.tenant_rate_tau_s)

    def _note_tenant_tokens(self, tenant: str, n: int) -> None:
        now = self._clock()
        tau = self.config.tenant_rate_tau_s
        r = self._tenant_rate.get(tenant, 0.0)
        if r:
            dt = max(0.0, now - self._tenant_rate_t[tenant])
            r *= math.exp(-dt / tau)
        self._tenant_rate[tenant] = r + n / tau
        self._tenant_rate_t[tenant] = now

    def _door_throttle_reason(self, request: Request) -> Optional[str]:
        """The FLEET-WIDE tenant-quota door check, against aggregates
        across replicas — the engine-level door (per-replica quotas)
        still runs behind it."""
        quotas = self.config.tenant_quotas
        q = None if quotas is None else quotas.get(request.tenant)
        if q is None:
            return None
        t = request.tenant
        alive = self._alive()
        if q.max_resident_blocks is not None:
            weight = (alive[0][1].engine.block_weight if alive else 1.0)
            worst = weight * blocks_needed(
                len(request.prompt) + request.max_new_tokens,
                self.engine_config.block_size)
            if worst > q.max_resident_blocks + 1e-9:
                return (f"needs up to {worst:g} block-units but is "
                        f"capped at max_resident_blocks="
                        f"{q.max_resident_blocks} fleet-wide")
            # the SUMMED check — the tenant's fractional resident
            # charge across every alive replica plus this request's
            # worst case must fit the fleet cap (the engine-level
            # quota holds an over-charge tenant at admission instead;
            # a fleet door has no queue to hold in, so it sheds)
            charge = sum(rep.engine.tenant_charge(t)
                         for _, rep in alive)
            if charge + worst > q.max_resident_blocks + 1e-9:
                return (f"holds {charge:.2f} resident block-units "
                        f"across the fleet and this request's worst "
                        f"case {worst:g} would break "
                        f"max_resident_blocks={q.max_resident_blocks}")
        if q.max_waiting is not None:
            depth = sum(rep.engine.tenant_depth(t)
                        for _, rep in alive)
            if depth >= q.max_waiting:
                return (f"already holds {depth} waiting entries across "
                        f"the fleet (max_waiting={q.max_waiting})")
        if q.tokens_per_s is not None:
            rate = self._tenant_rate_now(t)
            if rate > q.tokens_per_s:
                return (f"is over its fleet-wide token-rate budget "
                        f"({rate:.1f} > {q.tokens_per_s} tokens/s)")
        return None

    def add_request(self, request: Request) -> None:
        """Route one request to the best replica. Raises
        :class:`TenantThrottledError` when the FLEET-WIDE quota sheds
        it (terminal ``"throttled"``, drained by :meth:`run` — same
        contract as the engine door); a replica-level quota shed
        propagates from the chosen replica likewise. A replica whose
        queue is full is skipped for the next-best one;
        :class:`QueueFullError` raises only when EVERY alive replica
        is full (the fleet's backpressure signal). Duplicate live or
        undrained uids raise ``ValueError`` — uid uniqueness is
        fleet-wide."""
        uid = request.uid
        if uid in self._owner:
            raise ValueError(
                f"request uid {uid!r} is already live in the fleet; "
                "pick a distinct uid or wait for its terminal result")
        if uid in self._statuses:
            raise ValueError(
                f"request uid {uid!r} has a terminal result "
                f"({self._statuses[uid]!r}) awaiting drain; run() "
                "before reusing the uid")
        reason = self._door_throttle_reason(request)
        if reason is not None:
            object.__setattr__(request, "status", "throttled")
            self._record_result(uid, [], "throttled",
                                tenant=request.tenant)
            self._num_throttled += 1
            if self._obs is not None:
                self._obs.record("shed", uid=uid, reason="throttled")
            raise TenantThrottledError(
                f"request {uid!r} throttled: tenant "
                f"{request.tenant!r} {reason}")
        placed = None
        prompt = list(request.prompt)
        hashes: Optional[List[str]] = None
        if self._shared is not None:
            # ONE walk serves both the placement ranking and the
            # post-placement shared-tier seeding
            hashes = self._seq_hashes(prompt)
        for idx, matched in self._ranked(prompt, stage="prefill",
                                         hashes=hashes):
            try:
                arrival = self.replicas[idx].engine.add_request(request)
            except QueueFullError:
                continue
            placed = (idx, matched)
            break
        if placed is None:
            self._num_rejected_queue_full += 1
            raise QueueFullError(
                f"request {uid!r} rejected: every alive replica's "
                "waiting queue is at max_waiting")
        idx, matched = placed
        self._num_routed += 1
        if matched > 0:
            self._num_affinity_hits += 1
        if self._sdc_enabled:
            # the request's PRNG identity: what a completed token
            # stream replays from, bit-for-bit, on any equal-config
            # replica (the cross-check's soundness anchor)
            self._sdc_arrivals[uid] = int(arrival)
        self._owner[uid] = idx
        self._requests[uid] = request
        self.replicas[idx].routed += 1
        self._num_accepted += 1
        if hashes:
            # fleet-wide prefix hit: seed the chosen replica's local
            # spill tier with the shared-tier run extending its own
            # match, so its _admit re-admits by the one-scatter upload
            self._seed_from_shared(idx, hashes, matched)

    def try_add(self, request: Request) -> bool:
        """Non-raising variant, mirroring the engine's: False on a
        fleet/replica quota shed or a fleet-wide queue-full;
        validation errors still raise."""
        try:
            self.add_request(request)
        except (QueueFullError, TenantThrottledError):
            return False
        return True

    def abort(self, uid: str) -> bool:
        """Cancel a live request on its owning replica (terminal
        ``"cancelled"``, drained like any result). False for a uid the
        fleet does not currently own."""
        idx = self._owner.get(uid)
        if idx is None:
            return False
        rep = self.replicas[idx]
        if not rep.alive or rep.engine is None:
            return False
        return rep.engine.abort(uid)

    def owners(self) -> Dict[str, int]:
        """Live uid -> owning replica index (a copy) — the chaos
        bench's victim bookkeeping, and an operator's 'where is my
        request' lookup."""
        return dict(self._owner)

    # -- the drive loop ----------------------------------------------------

    @property
    def has_work(self) -> bool:
        for rep in self.replicas:
            if not (rep.alive and rep.engine is not None):
                continue
            try:
                if rep.engine.has_work:
                    return True
            except ReplicaUnavailableError:
                # a dead process child IS work: the next step() runs
                # its failover (re-homing everything it owned)
                return True
        return False

    def step(self) -> bool:
        """One fleet tick: step every alive replica that holds work
        (catching replica death — exception escape or a
        ``health_patience`` no-progress streak — with failover), then
        drain every replica's stream events and terminal results into
        the router's fleet-wide maps. Returns whether anything
        progressed (a failover counts: it moved requests). With
        disaggregated roles the tick OPENS with the handoff sweep —
        started requests leave the prefill specialists before this
        tick's stepping, operating on last tick's fully-drained
        state."""
        self._num_ticks += 1
        self._handoff_tick()
        progressed = False
        for i in range(len(self.replicas)):
            rep = self.replicas[i]
            if not rep.alive or rep.engine is None:
                continue
            try:
                # has_work is inside the containment on purpose: for a
                # process replica it is an RPC, and a SIGKILLed child
                # surfaces ReplicaUnavailableError right here
                if not rep.engine.has_work:
                    rep.stall_streak = 0
                    continue
                p = rep.engine.step()
            except Exception as e:  # replica crash containment: any
                # escape — SimulatedCrash, CacheOutOfBlocks, a real
                # runtime error — is THIS replica dying, not the fleet
                self._fail_replica(i, f"{type(e).__name__}: {e}")
                progressed = True
                continue
            if p:
                rep.stall_streak = 0
                progressed = True
            else:
                rep.stall_streak += 1
                if rep.stall_streak >= self.config.health_patience:
                    self._fail_replica(i, "no-progress stall")
                    progressed = True
        self._drain_outputs()
        self._shared_tick()
        self._autoscale_tick()
        self._maybe_sdc_check()
        return progressed

    def run(self, return_status: bool = False):
        """Drive the fleet until every accepted request is terminal.
        Same result contract as :meth:`InferenceEngine.run` — ``{uid:
        tokens}``, or ``{uid: RequestResult}`` with
        ``return_status=True`` — except fleet-wide. No stall guard is
        needed here: a stalled replica is a health event (patience,
        then failover), and a request that stalls every replica hits
        the ``max_request_failovers`` quarantine, so the loop always
        terminates (possibly in :class:`FleetFailedError` when the
        last replica dies with respawn off)."""
        while self.has_work:
            self.step()
        self._drain_outputs()
        out, self._results = self._results, {}
        statuses, self._statuses = self._statuses, {}
        self._stream = []
        if return_status:
            return {uid: RequestResult(tokens=toks,
                                       status=statuses.get(uid,
                                                           "finished"))
                    for uid, toks in out.items()}
        return out

    def pop_stream_events(self) -> List[Tuple[str, int, bool]]:
        """The fleet-wide streaming feed, concatenated across replicas
        in drain order. Token events are EXACTLY-ONCE even under
        failover: a re-homed request re-deriving tokens the dead
        replica already streamed resumes below the router's delivery
        watermark, and those replays are suppressed. Terminal
        ``(uid, -1, True)`` sentinels are best-effort — one can be
        lost with a crashing replica whose verdict the checkpoint
        adoption recovers — so terminal truth belongs to :meth:`run`
        (always exactly-once)."""
        out, self._stream = self._stream, []
        return out

    def _drain_outputs(self) -> None:
        for i, rep in self._alive():
            # re-check at use time: draining one replica can RETIRE
            # another mid-loop (an SDC verdict intercepted in its
            # results fails the diverging owner, whose engine may
            # already sit later in this snapshot of the alive list)
            if rep.alive and rep.engine is not None:
                try:
                    self._drain_replica_outputs(rep.engine)
                except ReplicaUnavailableError as e:
                    # a process child died between step and drain —
                    # same containment as a step()-time crash
                    self._fail_replica(i, f"{type(e).__name__}: {e}")

    def _drain_replica_outputs(self, eng: InferenceEngine) -> None:
        for uid, tok, last in eng.pop_stream_events():
            if uid in self._sdc_pending:
                # cross-check replay traffic: verification-internal,
                # never delivered (the client already received the
                # original stream)
                continue
            req = self._requests.get(uid)
            if tok >= 0 and req is not None:
                pos = self._emit_pos.get(uid, 0)
                self._emit_pos[uid] = pos + 1
                hist = self._delivered.setdefault(uid, [])
                if pos < len(hist):
                    # a failover re-derivation replaying a token the
                    # dead replica already streamed: below the
                    # delivery watermark — suppressed, so the stream
                    # feed stays exactly-once for tokens and the
                    # tenant rate estimator never double-counts
                    continue
                hist.append(int(tok))
                self._note_tenant_tokens(req.tenant, 1)
            self._stream.append((uid, tok, last))
        for uid, res in eng.pop_results().items():
            cand = self._sdc_pending.pop(uid, None)
            if cand is not None:
                self._finish_sdc_check(cand, res)
                continue
            self._maybe_capture_sdc(uid, res)
            self._record_result(uid, res.tokens, res.status)

    def _record_result(self, uid: str, tokens: Sequence[int],
                       status: str,
                       tenant: Optional[str] = None) -> None:
        """First terminal verdict wins, fleet-wide: failover
        re-derivation can produce a second (bit-identical) result for
        a uid the router already delivered — counted, dropped."""
        if uid in self._statuses:
            self._num_duplicate_results += 1
            return
        if tenant is None:
            req = self._requests.get(uid)
            tenant = req.tenant if req is not None else DEFAULT_TENANT
        self._results[uid] = [int(t) for t in tokens]
        self._statuses[uid] = status
        tally = self._tenant_status.setdefault(tenant, {})
        tally[status] = tally.get(status, 0) + 1
        if uid in self._owner:
            self._num_terminal += 1
        self._owner.pop(uid, None)
        self._requests.pop(uid, None)
        self._refails.pop(uid, None)
        self._delivered.pop(uid, None)
        self._emit_pos.pop(uid, None)
        self._sdc_arrivals.pop(uid, None)

    # -- fleet SDC detection (docs/fleet.md, docs/robustness.md) -----------

    def _maybe_capture_sdc(self, uid: str, res: RequestResult) -> None:
        """Queue a just-completed request as a cross-check candidate.
        Eligibility is where bit-identical replay is CERTIFIED: a
        ``"finished"`` verdict with tokens, a known arrival identity
        (failover re-injections drew a fresh arrival the router never
        saw — their streams mix two identities and are not replayable
        from scratch), and greedy sampling whenever speculation is on
        (speculative span boundaries are schedule-dependent, so only
        greedy streams are replica-invariant under speculation)."""
        if not self._sdc_enabled:
            return
        if res.status != "finished" or not res.tokens:
            return
        arrival = self._sdc_arrivals.get(uid)
        req = self._requests.get(uid)
        owner = self._owner.get(uid)
        if arrival is None or req is None or owner is None:
            return
        if (req.sampling.temperature > 0
                and self.engine_config.spec_tokens > 0):
            return
        self._sdc_queue.append({
            "uid": uid, "owner": int(owner), "arrival": int(arrival),
            "prompt": [int(t) for t in req.prompt],
            "max_new_tokens": int(req.max_new_tokens),
            "eos_token_id": (None if req.eos_token_id is None
                             else int(req.eos_token_id)),
            "sampling": {"temperature": float(req.sampling.temperature),
                         "top_k": int(req.sampling.top_k),
                         "top_p": float(req.sampling.top_p)},
            "priority": int(req.priority), "tenant": str(req.tenant),
            "tokens": [int(t) for t in res.tokens],
        })

    def _maybe_sdc_check(self) -> None:
        """Every ``sdc_check_interval_ticks`` router ticks, replay ONE
        queued candidate on a replica other than its owner. The replay
        record carries the ORIGINAL arrival (the PRNG identity), an
        empty history, and a private ``__sdc__N`` uid; it runs through
        the verifier's ordinary scheduling and its result is
        intercepted at the drain — never delivered, never counted as
        accepted. Equal configs make the verifier's stream a
        bit-for-bit oracle for the original."""
        interval = self.config.sdc_check_interval_ticks
        if interval is None or self._num_ticks % interval:
            return
        alive = self._alive()
        if len(alive) < 2:
            return
        while self._sdc_queue:
            cand = self._sdc_queue.popleft()
            owner = cand["owner"]
            rep = self.replicas[owner]
            if not rep.alive or rep.engine is None:
                continue    # the owner is already gone; nothing to vet
            verifiers = [i for i, _ in alive if i != owner]
            if not verifiers:
                return
            if self._launch_replay(cand, verifiers[0]):
                return      # one replay per interval — the budget

    def _launch_replay(self, cand: Dict, vidx: int) -> bool:
        """Import one replay record onto replica ``vidx`` and register
        the pending check. False when the replay record itself was
        refused in transit (its own "import" corruption) — the check
        is simply dropped."""
        ruid = f"__sdc__{self._sdc_seq}"
        self._sdc_seq += 1
        rec = seal_record({
            "uid": ruid, "prompt": list(cand["prompt"]),
            "max_new_tokens": cand["max_new_tokens"],
            "eos_token_id": cand["eos_token_id"],
            "sampling": dict(cand["sampling"]),
            "arrival": cand["arrival"],
            "priority": cand["priority"],
            # a dedicated INTERNAL tenant, not the original: the
            # replay must not charge the real tenant's resident-block
            # quota or delivered-token ledger on the verifier
            # (verification traffic the client never receives would
            # hold/throttle the tenant's own requests and inflate its
            # fleet-wide usage row). Unlisted and transient, so the
            # engine's idle-tenant pruning drops the row afterwards.
            # Tenant is never a sampling input, so replay identity is
            # unaffected.
            "tenant": _SDC_TENANT,
            "generated": [],
            # out-of-band of the verifier's DRR walk, like a
            # requeue: verification traffic must not contend for
            # (or distort) tenant fairness
            "drr_charged": True,
        })
        try:
            self.replicas[vidx].engine.import_requests([rec])
        except IntegrityError:
            return False
        cand["verifier"] = vidx
        self._sdc_pending[ruid] = cand
        self._num_sdc_checks += 1
        return True

    def _finish_sdc_check(self, cand: Dict, res: RequestResult) -> None:
        """Compare a drained replay against the original verdict. A
        non-"finished" replay (the verifier shed or timed it out) is
        inconclusive — no verdict, no retirement; a VOIDED check (the
        owner died of something else while the replay was in flight —
        or a respawn took its slot, which must not inherit the
        suspicion) is swallowed verdict-free. A token mismatch is
        PROOF of a defect (equal configs, equal PRNG identity) but
        does not say on WHICH side, so divergence ARBITRATES when a
        third replica exists: one confirmation replay on a replica
        independent of both owner and first verifier, and the side the
        majority contradicts retires —

        - confirmation == original  ⇒ the first VERIFIER diverged
          alone: it is the corrupt one;
        - confirmation != original  ⇒ two independent replicas
          contradict the owner's stream: the OWNER is the corrupt one.

        With only two replicas alive there is no arbiter and the owner
        retires (the documented asymmetry: a corrupt verifier then
        costs one healthy replica, and its own results keep failing
        later rounds). Retirement goes through the failover path with
        host state UNTRUSTED — checkpoints and buffered outputs of a
        silently-corrupting replica prove nothing, so its live
        requests re-inject fresh from the router's own copies (zero
        lost accepted requests, the PR 12 cert)."""
        if cand.get("void") or res.status != "finished":
            return
        replay = [int(t) for t in res.tokens]
        if replay == cand["tokens"]:
            if cand.get("confirm") \
                    and cand.get("first_verifier") is not None:
                # the arbiter sides with the original: the FIRST
                # verifier is the one that computed a wrong stream
                self._retire_suspect(cand["first_verifier"],
                                     cand["uid"])
            return
        if not cand.get("confirm"):
            arbiters = [i for i, _ in self._alive()
                        if i != cand["owner"]
                        and i != cand.get("verifier")]
            # a failed confirm launch (the replay record itself rotted
            # in transit) must NOT drop the proven divergence: fall
            # through to the no-arbiter verdict instead
            if arbiters and self._launch_replay(
                    dict(cand, confirm=True,
                         first_verifier=cand.get("verifier")),
                    arbiters[0]):
                return
        self._retire_suspect(cand["owner"], cand["uid"])

    def _retire_suspect(self, idx: int, uid: str) -> None:
        rep = self.replicas[idx]
        if not rep.alive or rep.engine is None:
            return  # a verdict against a corpse is stale evidence
        self._num_sdc_suspects += 1
        if self._obs is not None:
            self._obs.record("sdc_suspect", replica=idx, uid=uid)
        self._fail_replica(idx, "sdc divergence",
                           read_host_state=False,
                           trust_state=False)

    def _note_refused_import(self, uid, detail: str) -> None:
        """The one funnel for refused-import bookkeeping (counter +
        recorder), shared by the migrate, failover-placement, and
        source-requeue refusal paths."""
        self._num_refused_imports += 1
        if self._obs is not None:
            self._obs.record("corruption_detected", site="import",
                             uid=uid, detail=str(detail))

    def _drop_sdc_for_replica(self, idx: int) -> None:
        """Forget cross-check state touching a dead replica: queued
        candidates whose owner it was (nothing left to vet — and a
        respawn into the slot must not inherit their suspicion) and
        in-flight replays it was verifying (their results died with
        it). Replays whose OWNER died stay in the pending map but are
        VOIDED: the replay request itself is still live on its
        verifier, so its eventual result must still be intercepted
        (swallowed verdict-free) — dropping the map entry would let a
        ``__sdc__`` uid fall through to the client-facing result maps."""
        if not self._sdc_enabled:
            return
        self._sdc_queue = deque(
            (c for c in self._sdc_queue if c["owner"] != idx),
            maxlen=self._sdc_queue.maxlen)
        self._sdc_pending = {
            r: c for r, c in self._sdc_pending.items()
            if c.get("verifier") != idx}
        for c in self._sdc_pending.values():
            if c["owner"] == idx:
                c["void"] = True
            elif c.get("confirm") and c.get("first_verifier") == idx:
                # the accused first verifier died of something else
                # mid-arbitration: its half of the verdict is moot (a
                # respawn into the slot must not inherit the blame);
                # the owner half still stands
                c["first_verifier"] = None

    # -- elastic autoscaling (docs/fleet.md, "Autoscaler") -----------------

    def _autoscale_tick(self) -> None:
        """One control-loop tick, run every router tick after the
        drain: read the signal (mean queue depth per alive replica —
        pure ``load()`` reads, so a disabled or never-firing
        autoscaler perturbs nothing, which is the identity cert),
        debounce it through the consecutive-tick patience counters,
        and act at most once — spawn on a sustained high-watermark
        breach, retire on a sustained low one. Both streaks reset
        after any action (a fresh replica deserves a fresh
        measurement), and the min/max bounds gate the STREAKS, not
        just the action, so a fleet pinned at a bound does not hold a
        primed trigger."""
        hi = self.config.autoscale_high_watermark
        lo = self.config.autoscale_low_watermark
        if hi is None and lo is None:
            return
        alive = self._alive()
        if not alive:
            return
        # the signal is PER-ROLE (docs/fleet.md, "Disaggregated
        # roles"): mean queue depth over the alive replicas of each
        # role, so a prefill backlog is never masked by idle decode
        # replicas (or vice versa). A colocated fleet has the single
        # role "mixed" — one group, the exact pre-role signal.
        groups: Dict[str, List] = {}
        for i, rep in alive:
            groups.setdefault(rep.role, []).append((i, rep))
        maxr = self.config.autoscale_max_replicas
        can_grow = maxr is None or len(alive) < maxr
        acted = False
        for role in sorted(groups):
            members = groups[role]
            try:
                depth = sum(rep.engine.load()["queue_depth"]
                            for _, rep in members) / len(members)
            except ReplicaUnavailableError:
                continue    # a child died mid-read; step() contains it
            if acted:
                continue    # one action per tick; later roles' streaks
                # simply hold (neither advanced nor disarmed)
            # shrink bounds: the fleet-wide floor, plus never the last
            # replica of a specialist role (a roleless fleet's single
            # "mixed" group is bounded by the floor alone)
            can_shrink = (len(alive)
                          > self.config.autoscale_min_replicas
                          and (not self._roles_enabled
                               or len(members) > 1))
            hi_s = self._as_hi_streaks.get(role, 0)
            lo_s = self._as_lo_streaks.get(role, 0)
            hi_s = (hi_s + 1 if (hi is not None and depth > hi
                                 and can_grow) else 0)
            lo_s = (lo_s + 1 if (lo is not None and depth < lo
                                 and can_shrink) else 0)
            if hi_s >= self.config.autoscale_patience:
                hi_s = lo_s = 0
                self._scale_up(role)
                acted = True    # at most one action per tick
            elif lo_s >= self.config.autoscale_patience:
                hi_s = lo_s = 0
                self._scale_down(role)
                acted = True
            self._as_hi_streaks[role] = hi_s
            self._as_lo_streaks[role] = lo_s
        # the pre-role scalar views (tests and dashboards read them;
        # exact for colocated fleets, the max across roles otherwise)
        self._autoscale_hi_streak = max(self._as_hi_streaks.values(),
                                        default=0)
        self._autoscale_lo_streak = max(self._as_lo_streaks.values(),
                                        default=0)

    def _scale_up(self, role: str = "mixed") -> None:
        """Append one fresh replica slot (same spawn path respawn
        uses) of the breaching role and warm its prefix cache from
        the survivors — an autoscaled newcomer should serve affinity
        traffic, not start from a cold index."""
        idx = len(self.replicas)
        self._drafters.append(None)
        self._faults.append(None)
        self._roles.append(role)
        self._published.append(set())
        self.replicas.append(self._spawn(idx))
        self._num_spawned += 1
        if self._obs is not None:
            self._obs.record("replica_spawn", replica=idx,
                             reason="autoscale", role=role)
        try:
            self._warm_replica(idx)
        except Exception:
            pass    # warm-up is an optimization, never a dependency

    def _warm_replica(self, idx: int) -> None:
        """Seed a newcomer's prefix cache with the KV payloads of live
        prompts (``export_prefix_payloads`` on each owner ->
        ``import_prefix_payloads`` on the newcomer) — the migration
        transport, reused as a warm-up. Needs a spill tier on both
        ends; silently a no-op otherwise."""
        if not self.config.migrate_spill_payloads:
            return
        target = self.replicas[idx].engine
        for uid, owner in sorted(self._owner.items()):
            rep = self.replicas[owner]
            req = self._requests.get(uid)
            if req is None or not rep.alive or rep.engine is None:
                continue
            payloads = rep.engine.export_prefix_payloads(
                self._seq_hashes(list(req.prompt)))
            if payloads:
                target.import_prefix_payloads(payloads)

    def _scale_down(self, role: str = "mixed") -> None:
        """Retire one replica of the under-loaded role through the
        clean drain-and-migrate path. The victim is deterministic:
        fewest owned live requests (cheapest drain), ties to the
        HIGHEST index (autoscaled slots retire before the original
        fleet)."""
        alive = [(i, rep) for i, rep in self._alive()
                 if rep.role == role]
        if not alive:
            return
        owned: Dict[int, int] = {i: 0 for i, _ in alive}
        for o in self._owner.values():
            if o in owned:
                owned[o] += 1
        victim = min((i for i, _ in alive),
                     key=lambda i: (owned[i], -i))
        try:
            self.drain_replica(victim, retire=True)
        except ValueError:
            return      # last-replica-with-work refusal: not this tick
        self._num_retired += 1
        if self._obs is not None:
            self._obs.record("replica_retire", replica=victim,
                             reason="autoscale", role=role)

    # -- fleet-global shared prefix tier (docs/fleet.md, "Shared
    # prefix tier") --------------------------------------------------------

    def _note_shared_corrupt(self, site: str, block_hash: str) -> None:
        """The shared store's ``on_corrupt`` hook (and the publish
        verifier's): surface every shared-tier detection to the flight
        recorder under a ``shared_``-prefixed site, mirroring the
        engine's one-funnel discipline. The discard count itself lives
        on the store (``num_shared_corrupt_discards``)."""
        if self._obs is not None:
            self._obs.record("corruption_detected",
                             site=f"shared_{site}",
                             detail=str(block_hash))

    def _publish_payload(self, block_hash: str, payload: Dict,
                         tenant: str) -> bool:
        """Verify one transported payload end-to-end (against the
        detached checksum the export attached), then publish it into
        the shared tier. A mismatch is transport rot: reported and
        skipped — the shared tier must never launder corrupt bytes
        fleet-wide, and a skip just means the block stays a miss."""
        payload = dict(payload)
        checksum = payload.pop("checksum", None)
        if (self.engine_config.verify_artifacts
                and checksum is not None):
            try:
                verify_payload(payload, checksum, "shared_publish")
            except IntegrityError:
                self._note_shared_corrupt("publish", block_hash)
                return False
        if self._shared.publish(block_hash, payload, tenant=tenant):
            self._num_shared_publishes += 1
            return True
        return False

    def _shared_tick(self) -> None:
        """The per-tick shared-tier sweep (a no-op with the tier off —
        certified bit-identical to the tier-less fleet). PUBLISH: every
        local-spill entry a replica holds that its slot has not
        published yet enters the tier — payloads ride
        ``export_prefix_payloads`` (the framed-RPC spill surface
        process replicas already speak), entries the tier already holds
        publish as dedupe references (no bytes moved). Then SCRUB
        ``shared_scrub_blocks`` entries round-robin (the engine spill
        scrubber's budgeted-cursor discipline, walked by the router)
        and audit the refcount/ownership/byte ledger."""
        if self._shared is None:
            return
        for i, rep in self._alive():
            try:
                spilled = rep.engine.spilled_hashes()
            except ReplicaUnavailableError:
                continue
            fresh = [h for h in spilled
                     if h not in self._published[i]]
            if not fresh:
                continue
            need = [h for h in fresh if h not in self._shared]
            payloads: Dict[str, Dict] = {}
            if need:
                try:
                    payloads = rep.engine.export_prefix_payloads(need)
                except ReplicaUnavailableError:
                    continue
            stored = 0
            nbytes = 0
            for h in fresh:
                if h in self._shared:
                    # content-addressed dedupe: the same hash from a
                    # second slot adds a reference and an ownership
                    # share, never a second copy
                    self._shared.publish(h, None, tenant=spilled[h])
                    self._published[i].add(h)
                    continue
                payload = payloads.get(h)
                if payload is None:
                    # rotted (and discarded) mid-export, or past an
                    # export gap: not published, retried next tick
                    continue
                if self._publish_payload(h, payload, spilled[h]):
                    stored += 1
                    nbytes += self._payload_nbytes({h: payload})
                self._published[i].add(h)
            if stored and self._obs is not None:
                self._obs.record("shared_publish", replica=i,
                                 blocks=stored, bytes=nbytes)
        n = self.config.shared_scrub_blocks
        if n > 0:
            verified, _ = self._shared.scrub(n)
            self._num_shared_scrub_blocks_verified += verified
        # the dedupe/byte ledger audit every tick — cheap, host-side,
        # and a violated shared ledger has no safe degradation
        self._shared.check_integrity()

    def _seed_from_shared(self, idx: int, hashes: Sequence[str],
                          matched: int) -> int:
        """The fleet-wide prefix HIT path: fetch the contiguous
        shared-tier run extending what replica ``idx`` already serves
        (device index, then local spill — ``matched``) and seed it
        into the replica's local spill tier through
        ``import_prefix_payloads`` (the framed-RPC spill transport in
        process mode). The replica's next ``_admit`` finds a
        contiguous spilled run and re-admits it via the existing
        one-scatter upload path — token-identical to recompute, by the
        spill-tier equivalence cert. Returns blocks accepted (0
        without a local spill tier on the replica: the tier is an
        optimization, never a dependency)."""
        if self._shared is None:
            return 0
        payloads: Dict[str, Dict] = {}
        n = int(matched)
        while n < len(hashes) and hashes[n] in self._shared:
            payload = self._shared.fetch(hashes[n])
            if payload is None:
                break   # rot: discarded with its references — a miss
            if self.engine_config.verify_artifacts:
                # the detached transport checksum, same as the
                # replica-to-replica export path — the importing
                # engine verifies the bytes end to end
                payload["checksum"] = payload_checksum(payload)
            payloads[hashes[n]] = payload
            n += 1
        if not payloads:
            return 0
        try:
            accepted = self.replicas[idx].engine.import_prefix_payloads(
                payloads)
        except ReplicaUnavailableError:
            return 0
        if accepted:
            self._num_shared_hits += accepted
            if self._obs is not None:
                self._obs.record("shared_hit", replica=idx,
                                 blocks=accepted,
                                 bytes=self._payload_nbytes(payloads))
        return accepted

    # -- disaggregated handoff (docs/fleet.md, "Disaggregated roles") ------

    def _handoff_tick(self) -> None:
        """The per-tick prefill->decode handoff sweep: every started
        request (prefill complete, first token known) on a
        prefill-specialist replica migrates to a decode specialist
        through the checksummed drain-and-migrate transport — records
        carry the emitted tokens and arrival identity (resume is
        bit-identical, the PR 12 cert), KV payloads ride the spill
        tier so the decode side re-admits as a prefix hit instead of
        recomputing, and a refused (corrupt) import leaves the request
        on its source exactly like any migration refusal. A no-op for
        colocated fleets."""
        if not self._roles_enabled:
            return
        for i, rep in self._alive():
            if rep.role != "prefill" or not rep.alive \
                    or rep.engine is None:
                continue
            try:
                uids = [u for u in rep.engine.decoding_uids()
                        if u not in self._sdc_pending]
            except ReplicaUnavailableError as e:
                self._fail_replica(i, f"{type(e).__name__}: {e}")
                continue
            if uids:
                try:
                    self.migrate(uids, i, _handoff=True)
                except ReplicaUnavailableError as e:
                    self._fail_replica(i, f"{type(e).__name__}: {e}")

    @staticmethod
    def _payload_nbytes(payloads: Mapping[str, Dict]) -> int:
        """Approximate wire size of a handoff's KV payloads — array
        leaves by their buffer size, strings/bytes by length (the
        ``num_handoff_bytes`` gauge; observability, not billing)."""
        n = 0
        for payload in payloads.values():
            for v in payload.values():
                if hasattr(v, "nbytes"):
                    n += int(v.nbytes)
                elif isinstance(v, (bytes, bytearray, str)):
                    n += len(v)
                elif isinstance(v, (list, tuple)):
                    n += 8 * len(v)
        return n

    # -- health, failover, migration ---------------------------------------

    def _fail_replica(self, idx: int, reason: str,
                      read_host_state: bool = True,
                      trust_state: bool = True) -> None:
        """Declare a replica dead and fail over. ``read_host_state``
        distinguishes the two death modes: an in-process exception
        escape leaves the engine OBJECT's host bookkeeping intact —
        :meth:`InferenceEngine.checkpoint` is pure host reads, so a
        fresh checkpoint beats a stale one — while a simulated hard
        kill (:meth:`kill_replica`) forbids touching the corpse and
        recovery runs from ``last_checkpoint`` alone.
        ``trust_state=False`` is the SDC-suspect mode: nothing the
        replica wrote is believed — no drain, no checkpoint (its
        records carry tokens a corrupt chip computed) — and every
        live request it owned re-injects FRESH from the router's own
        copies. Whatever checkpoint IS used must verify its content
        checksum first (``verify_artifacts``): a corrupt checkpoint
        reads as no checkpoint, the same fresh re-injection path."""
        rep = self.replicas[idx]
        rep.alive = False
        rep.error = reason
        # the slot's publish ledger dies with its spill tier: a
        # respawn into the slot starts cold and may legitimately
        # re-publish (a fresh reference from a fresh holder)
        self._published[idx] = set()
        self._num_replicas_down += 1
        if self._obs is not None:
            self._obs.record("replica_down", replica=idx,
                             reason=reason, role=rep.role)
        snap = None
        if rep.engine is not None and trust_state:
            snap = rep.engine.last_checkpoint
            if read_host_state:
                # the engine OBJECT survived (in-process death): its
                # buffered stream events and terminal results are
                # intact host state — collect them BEFORE the fresh
                # checkpoint, or the checkpoint's records would carry
                # tokens the router never delivered and the delivery
                # watermark would anchor past them (a silent token
                # gap in the exactly-once stream feed)
                try:
                    self._drain_replica_outputs(rep.engine)
                except Exception:
                    pass
                try:
                    snap = rep.engine.checkpoint()
                except Exception:
                    pass  # keep the periodic checkpoint (or None)
        if not read_host_state:
            rep.engine = None   # the process is gone; so is the object
        elif rep.mode == "process" and rep.engine is not None:
            # a process replica's corpse is a real child process:
            # whatever could be read was read above — now reap it (a
            # dead handle cannot serve stats either, so the slot
            # drops the object like the hard-kill path does)
            try:
                rep.engine.kill()
            except Exception:
                pass
            rep.engine = None
        # integrity gate (docs/robustness.md): the failover picture is
        # believed only if its content checksum verifies — a corrupt
        # checkpoint is refused and recovery falls back to the fresh
        # re-injection path the zero-lost cert already covers
        snap = self._checked_checkpoint(snap)
        # purge cross-check state touching the corpse AFTER its
        # buffered outputs were drained (a completed replay verdict in
        # that buffer was still intercepted above), so nothing of a
        # replay uid can ever leak into the client-facing result maps
        self._drop_sdc_for_replica(idx)
        if self.config.respawn:
            # the fresh engine takes the slot and joins the survivors
            # as a re-homing target; the dead _Replica (and its error)
            # is dropped — its story lives in the counters/recorder
            self.replicas[idx] = self._spawn(idx)
            self._num_respawns += 1
        self._failover(idx, snap, reason)

    def _checked_checkpoint(self, snap: Optional[Dict]
                            ) -> Optional[Dict]:
        """Verify a failover checkpoint's embedded checksum before ANY
        of it is believed (adoption, re-imports). Returns None — "no
        checkpoint", the certified fresh-re-inject path — on a
        mismatch; checksum-less legacy checkpoints pass through (the
        detection guarantee covers sealed artifacts only)."""
        if snap is None or not self.engine_config.verify_artifacts:
            return snap
        try:
            verify_record(snap, "checkpoint")
        except IntegrityError as e:
            self._num_corrupt_checkpoints += 1
            if self._obs is not None:
                self._obs.record("corruption_detected",
                                 site="checkpoint", detail=e.detail)
            return None
        return snap

    def _failover(self, idx: int, snap: Optional[Dict],
                  reason: str) -> None:
        """Re-home everything the dead replica owned (docs/fleet.md,
        the zero-lost-request contract): adopt checkpointed terminal
        results, re-import checkpointed live entries (emitted tokens +
        arrival identity preserved; post-checkpoint tokens re-derive),
        re-inject post-checkpoint accepts fresh from the router's own
        Request copies, and terminal-fail any request past its
        ``max_request_failovers`` budget."""
        self._num_failovers += 1
        owned = [uid for uid, o in self._owner.items() if o == idx]
        owned_set = set(owned)
        recs = {r["uid"]: r
                for r in (snap or {}).get("requests", ())}
        fin = (snap or {}).get("finished") or {}
        statuses = (snap or {}).get("statuses") or {}
        # results that went terminal between the router's last drain
        # and the checkpoint: adopt, never recompute. ONLY for uids
        # the dead replica still OWNS — a stale checkpoint (e.g. one
        # predating a full run() cycle) can list finished uids from
        # finished-and-delivered lifetimes, and adopting those would
        # resurrect already-delivered results (the dedupe map was
        # cleared by run()) or even disown a REUSED uid now live on a
        # survivor, handing the caller the old lifetime's tokens.
        adopted = 0
        for uid, toks in fin.items():
            if uid in owned_set:
                self._record_result(uid, toks,
                                    statuses.get(uid, "finished"))
                adopted += 1
        rehomed = 0
        for uid in owned:
            if uid in self._statuses:
                continue    # adopted just above
            self._refails[uid] = self._refails.get(uid, 0) + 1
            rec = recs.get(uid)
            if self._refails[uid] > self.config.max_request_failovers:
                # the router-level quarantine: this request has now
                # taken down more replicas than it is worth. Keep the
                # LONGER of the delivered watermark and the checkpoint
                # record (delivered is never behind a drained stream,
                # but belt-and-braces beats a result shorter than what
                # the consumer already received)
                gen = [int(t) for t in self._delivered.get(uid, ())]
                if rec and len(rec.get("generated", ())) > len(gen):
                    gen = [int(t) for t in rec["generated"]]
                self._num_router_failed += 1
                self._record_result(uid, gen, "failed")
                continue
            if rec is None:
                # accepted after the checkpoint: the checkpoint never
                # saw it, but the router holds the Request — re-inject
                # fresh, CARRYING the tokens the router already
                # delivered (the watermark history): a fresh arrival
                # identity redraws only FUTURE tokens, so the stream a
                # consumer received stays a prefix of the terminal
                # result instead of being contradicted by re-derived
                # draws under the new key
                rec = _request_record(self._requests[uid])
                rec["generated"] = [int(t) for t in
                                    self._delivered.get(uid, ())]
                self._num_reinjected_requests += 1
            self._place_record(rec)
            rehomed += 1
        if self._obs is not None:
            self._obs.record("failover", replica=idx, reason=reason,
                             rehomed=rehomed,
                             adopted=adopted,
                             checkpointed=len(recs))

    def _place_record(self, rec: Dict, retried: bool = False) -> None:
        """Route one entry record to the best surviving replica and
        import it there. One at a time so each placement sees the
        queue depth the previous one created. The record is SEALED for
        this hop (checkpoint-internal records were verified as part of
        the checkpoint, but travel unsealed); a target that refuses it
        on a checksum mismatch (in-transit rot) triggers ONE retry
        from the router's own clean ``Request`` copy — the same fresh
        re-injection the rec-is-None failover path certifies, losing
        checkpoint history beyond the delivered watermark but losing
        no request — and only a second refusal (or a record the router
        holds no copy of) terminal-fails with what the router already
        delivered: the poison-quarantine verdict, still zero-lost (a
        verdict is not a loss)."""
        uid = rec["uid"]
        seq = list(rec["prompt"]) + list(rec.get("generated", ()))[:-1]
        # role-aware failover: a record with generated history is
        # mid-decode and re-homes onto the decode specialists; a
        # waiting entry (or a fresh re-injection) still needs prefill.
        # _ranked degrades to any survivor when the role group is
        # empty — zero-lost outranks specialization.
        stage = "decode" if rec.get("generated") else "prefill"
        idx = self._ranked(seq, stage)[0][0]
        try:
            self.replicas[idx].engine.import_requests([seal_record(rec)])
        except IntegrityError as e:
            self._note_refused_import(uid, e.detail)
            req = self._requests.get(uid)
            if not retried and req is not None:
                fresh = _request_record(req)
                fresh["generated"] = [int(t) for t in
                                      self._delivered.get(uid, ())]
                self._num_reinjected_requests += 1
                self._place_record(fresh, retried=True)
                return
            gen = [int(t) for t in self._delivered.get(uid, ())]
            if len(rec.get("generated") or ()) > len(gen):
                gen = [int(t) for t in rec["generated"]]
            self._num_router_failed += 1
            self._record_result(uid, gen, "failed")
            return
        self._owner[uid] = idx
        if self._sdc_enabled:
            # cross-check eligibility survives a re-homing only when
            # the verdict would still be ATTRIBUTABLE: the arrival
            # identity must be known (a fresh re-injection draws one
            # the router never learns) AND the record must carry no
            # generated history — tokens computed by the PREVIOUS
            # owner ride the record, so the final stream mixes two
            # replicas' compute and a divergence could blame a healthy
            # replica for a dead one's corruption
            if (rec.get("arrival") is not None
                    and not rec.get("generated")):
                self._sdc_arrivals[uid] = int(rec["arrival"])
            else:
                self._sdc_arrivals.pop(uid, None)
        # the new owner resumes emission after the record's history:
        # anchor the delivery watermark's cursor there, so any
        # re-derivation of already-streamed tokens is suppressed
        self._emit_pos[uid] = len(rec.get("generated") or ())
        self.replicas[idx].routed += 1

    def kill_replica(self, idx: int) -> None:
        """Chaos hook: simulate ABRUPT replica death (SIGKILL
        semantics) — the engine object is discarded unread, and
        failover recovers from ``last_checkpoint`` plus the router's
        own routing record alone. The honest test of the
        bounded-staleness checkpoint contract; an exception escaping
        ``step()`` exercises the softer in-process path instead."""
        rep = self.replicas[idx]
        if not rep.alive or rep.engine is None:
            raise ValueError(f"replica {idx} is not alive")
        if rep.mode == "process":
            # a REAL SIGKILL, not a simulation: the child OS process
            # dies mid-whatever-it-was-doing; recovery still runs from
            # the parent-cached last_checkpoint alone, same contract
            rep.engine.kill()
        self._fail_replica(idx, "killed", read_host_state=False)

    def migrate(self, uids: Optional[Sequence[str]], src: int,
                dst: Optional[int] = None, *,
                _handoff: bool = False) -> int:
        """Drain-and-migrate: move the given live requests (all of the
        source's, when ``uids`` is None) off replica ``src`` — onto
        ``dst``, or onto whatever the placement score picks per
        request. The source exports drained entry records (its
        in-flight decode synced, blocks released, deadlines serialized
        as remaining budget); the target imports and re-prefills
        through its prefix cache, optionally seeded with the prompt's
        KV payloads through the spill tier
        (``migrate_spill_payloads``). Equal seeds across the fleet
        make the migrated request's token stream bit-identical to the
        unmigrated one (certified). Returns how many requests moved."""
        rep = self.replicas[src]
        if not rep.alive or rep.engine is None:
            raise ValueError(f"replica {src} is not alive")
        if dst is not None:
            drep = self.replicas[dst]
            if dst == src or not drep.alive or drep.engine is None:
                raise ValueError(
                    f"migration target {dst} is not a distinct alive "
                    "replica")
        records = rep.engine.export_requests(uids)
        moved = 0
        nbytes = 0
        for rec in records:
            uid = rec["uid"]
            seq = (list(rec["prompt"])
                   + list(rec.get("generated", ()))[:-1])
            # ONE chain-hash walk per placement decision: the payload
            # export, the handoff publish, and the placement ranking
            # below all read the same chain
            hashes = self._seq_hashes(seq)
            payloads = None
            if self.config.migrate_spill_payloads:
                payloads = rep.engine.export_prefix_payloads(hashes)
                if payloads:
                    nbytes += self._payload_nbytes(payloads)
            if payloads and _handoff and self._shared is not None:
                # publish-then-import: the prefill specialist's work
                # becomes visible FLEET-WIDE before (not instead of)
                # the decode target's point-to-point import below
                self._publish_handoff(src, rec, payloads)
            if dst is not None:
                idx = dst
            else:
                # two-stage under roles: a record with generated
                # history is mid-decode (rank the decode specialists),
                # a plain waiting entry still needs its prefill
                stage = "decode" if rec.get("generated") else "prefill"
                ranked = [i for i, _
                          in self._ranked(seq, stage, hashes=hashes)
                          if i != src]
                idx = ranked[0] if ranked else src
            target = self.replicas[idx].engine
            if payloads:
                target.import_prefix_payloads(payloads)
            try:
                target.import_requests([rec])
            except IntegrityError as e:
                # the record rotted between the source's seal and the
                # target's verify: REFUSED — corrupt state never
                # re-enters the fleet, and the request stays the
                # source's (re-injected there fresh from the router's
                # own clean copy, carrying the delivered watermark)
                self._note_refused_import(uid, e.detail)
                self._requeue_refused(rec, src)
                continue
            if uid in self._sdc_pending:
                # a cross-check replay swept up by the drain: result
                # interception is by uid, so just re-point its
                # verifier — replays are never owner-tracked
                self._sdc_pending[uid]["verifier"] = idx
            else:
                self._owner[uid] = idx
                self._emit_pos[uid] = len(rec.get("generated") or ())
                if rec.get("generated"):
                    # migrated WITH history: the final stream mixes
                    # the source's compute with the target's, so an
                    # eventual divergence could not be attributed to
                    # either — it leaves the cross-check pool
                    self._sdc_arrivals.pop(uid, None)
            self.replicas[idx].routed += 1
            moved += 1
        if records:
            self._num_migrations += 1
            self._num_migrated_requests += moved
            if self._obs is not None:
                self._obs.record("migrate", src=src,
                                 dst=(dst if dst is not None else -1),
                                 requests=moved)
            if _handoff:
                self._num_handoffs += 1
                self._num_handoff_requests += moved
                self._num_handoff_bytes += nbytes
                if self._obs is not None:
                    self._obs.record(
                        "prefill_handoff", src=src, requests=moved,
                        bytes=nbytes,
                        prefill_queue=self._role_backlog("prefill"),
                        decode_queue=self._role_backlog("decode"))
        return moved

    def _publish_handoff(self, src: int, rec: Dict,
                         payloads: Mapping[str, Dict]) -> None:
        """Publish one handoff's exported KV payloads into the shared
        tier, attributed to the request's tenant — the
        publish-then-import half of ``_handoff_tick``. Hashes the
        source slot already published become dedupe references; the
        publish-once-per-slot ledger keeps repeated handoffs of the
        same hot prefix from inflating refcounts."""
        tenant = str(rec.get("tenant", DEFAULT_TENANT))
        stored = 0
        nbytes = 0
        for h, payload in payloads.items():
            if h in self._published[src]:
                continue
            if h in self._shared:
                self._shared.publish(h, None, tenant=tenant)
            elif self._publish_payload(h, payload, tenant):
                stored += 1
                nbytes += self._payload_nbytes({h: payload})
            self._published[src].add(h)
        if stored and self._obs is not None:
            self._obs.record("shared_publish", replica=src,
                             blocks=stored, bytes=nbytes)

    def _role_backlog(self, role: str) -> int:
        """Summed backlog (waiting + active lanes) over the alive
        replicas of one role — the handoff event's per-role queue
        snapshot and the trace summary's disaggregation line."""
        total = 0
        for i, rep in self._alive():
            if rep.role != role:
                continue
            try:
                ld = rep.engine.load()
            except ReplicaUnavailableError:
                continue
            total += int(ld["queue_depth"] + ld["active_slots"])
        return total

    def _requeue_refused(self, rec: Dict, src: int) -> None:
        """A migration import was refused on a checksum mismatch: the
        exported record is untrustworthy, so the SOURCE keeps the
        request — re-injected fresh from the router's own Request copy
        (the same record the failover path certifies), carrying the
        delivered-token watermark so the client's stream stays a
        prefix of the terminal result. If even that hop is refused
        (corruption on the source's own import path), the request
        terminal-fails with its delivered tokens — the quarantine
        verdict, never a loss."""
        uid = rec.get("uid")
        req = self._requests.get(uid)
        rep = self.replicas[src]
        if req is None or not rep.alive or rep.engine is None:
            # a replay record (no router copy): the check is dropped
            self._sdc_pending.pop(uid, None)
            return
        fresh = _request_record(req)
        fresh["generated"] = [int(t) for t in
                              self._delivered.get(uid, ())]
        # the source's undrained stream events for this uid cover
        # exactly the tokens past the delivered watermark — the
        # recompute below re-derives (and re-emits) them
        # bit-identically, so the stale copies must go first or each
        # token would be delivered twice, shifting every later
        # position in the ledger
        rep.engine.drop_stream_events(uid)
        # the recompute must re-draw the SAME sampled tokens past the
        # delivered watermark: sampling is arrival-keyed, and the
        # rotted record's own arrival field is exactly what cannot be
        # trusted — the source engine kept a clean copy at export
        arrival = rep.engine.exported_arrival(uid)
        if arrival is not None:
            fresh["arrival"] = arrival
        try:
            rep.engine.import_requests([seal_record(fresh)])
        except IntegrityError as e:
            self._note_refused_import(uid, e.detail)
            self._num_router_failed += 1
            self._record_result(uid, list(fresh["generated"]), "failed")
            return
        self._owner[uid] = src
        self._emit_pos[uid] = len(fresh["generated"])
        self._num_reinjected_requests += 1
        self._sdc_arrivals.pop(uid, None)
        rep.routed += 1

    def drain_replica(self, src: int, dst: Optional[int] = None,
                      retire: bool = False) -> int:
        """Move EVERYTHING off replica ``src`` (one :meth:`migrate`
        call), optionally retiring it afterwards — the clean shutdown
        path: no failover, no checkpoint, nothing lost, the replica
        simply stops receiving placements. Refuses — before touching
        anything — to retire the LAST alive replica while it holds
        live requests: with nowhere to migrate them, retirement would
        strand them alive-but-unservable forever (the one hole the
        zero-lost gauge cannot see, since the requests stay live).
        Returns requests moved."""
        if retire:
            others = [i for i, _ in self._alive() if i != src]
            rep = self.replicas[src]
            if not others and rep.engine is not None \
                    and rep.engine.has_work:
                raise ValueError(
                    f"cannot retire replica {src}: it is the last "
                    "alive replica and still holds live requests — "
                    "nothing could ever serve them")
        moved = self.migrate(None, src, dst)
        if retire:
            rep = self.replicas[src]
            # the export's drain may have FINISHED lanes (EOS/budget
            # hit inside the synced dispatch): collect those verdicts
            # now — a retired replica leaves the per-tick drain loop,
            # and a result stranded on it would never be delivered
            self._drain_replica_outputs(rep.engine)
            rep.alive = False
            rep.error = "retired"
            self._published[src] = set()
            if rep.mode == "process":
                # clean shutdown of the child; a closed handle cannot
                # serve stats, so the slot drops the object
                try:
                    rep.engine.close()
                except Exception:
                    pass
                rep.engine = None
            if self._obs is not None:
                self._obs.record("replica_down", replica=src,
                                 reason="retired",
                                 role=self.replicas[src].role)
        return moved

    def close(self) -> None:
        """Dispose every process-replica child (graceful shutdown RPC,
        then reap). A no-op for in-process replicas and already-dead
        slots; the router object itself stays usable for ``stats()``
        reads afterwards but serves nothing."""
        for rep in self.replicas:
            if rep.mode == "process" and rep.engine is not None:
                try:
                    rep.engine.close()
                except Exception:
                    pass

    # -- observability -----------------------------------------------------

    def stats(self) -> Dict[str, object]:
        """The fleet counters (docs/fleet.md): routing, health,
        failover, migration, and the zero-lost invariant as a gauge —
        ``num_lost_requests`` is accepted minus live minus terminal
        and must read 0 always (the chaos bench asserts it). Nested:
        ``replicas`` (per-slot health + load view) and ``tenants``
        (the fleet-wide ledger: per-replica rows summed, the router's
        door tallies and rate estimator merged in)."""
        alive = self._alive()
        reps: Dict[str, Dict[str, object]] = {}
        tenant_rows: List[Dict[str, Dict[str, object]]] = []
        for i, rep in enumerate(self.replicas):
            row: Dict[str, object] = {
                "alive": bool(rep.alive and rep.engine is not None),
                "mode": rep.mode,
                "role": rep.role,
                "routed": rep.routed,
                "stall_streak": rep.stall_streak,
                "error": rep.error,
            }
            if rep.engine is not None:
                es = rep.engine.stats()
                row.update(rep.engine.load())
                for k in ("num_checkpoints", "num_migrated_in",
                          "num_migrated_out", "num_preemptions",
                          "num_quarantines"):
                    row[k] = es[k]
                if rep.alive:
                    tenant_rows.append(es["tenants"])
            reps[str(i)] = row
        return {
            "num_replicas": len(self.replicas),
            "replicas_alive": len(alive),
            "num_ticks": self._num_ticks,
            "num_accepted": self._num_accepted,
            "num_routed": self._num_routed,
            "num_affinity_hits": self._num_affinity_hits,
            "num_failovers": self._num_failovers,
            "num_replicas_down": self._num_replicas_down,
            "num_respawns": self._num_respawns,
            "num_migrations": self._num_migrations,
            "num_migrated_requests": self._num_migrated_requests,
            "num_reinjected_requests": self._num_reinjected_requests,
            "num_duplicate_results": self._num_duplicate_results,
            "num_router_failed": self._num_router_failed,
            "num_rejected_queue_full": self._num_rejected_queue_full,
            "num_throttled": self._num_throttled,
            # data integrity (docs/robustness.md "Data integrity"):
            # refused failover checkpoints, refused migration/failover
            # imports, and the SDC cross-check's replay/verdict tally
            "num_corrupt_checkpoints": self._num_corrupt_checkpoints,
            "num_refused_imports": self._num_refused_imports,
            "num_sdc_checks": self._num_sdc_checks,
            "num_sdc_suspects": self._num_sdc_suspects,
            # process replicas + autoscaler (docs/fleet.md, "Process
            # replicas"): autoscaled spawns/retires and the RPC
            # frame-retry/timeout tally (always 0 in-process)
            "num_spawned": self._num_spawned,
            "num_retired": self._num_retired,
            "num_rpc_retries": self._num_rpc_retries,
            "num_rpc_timeouts": self._num_rpc_timeouts,
            # disaggregated prefill/decode roles (docs/fleet.md,
            # "Disaggregated roles"): handoff sweeps, requests moved
            # and payload bytes shipped prefill->decode, and the
            # affinity probes the two-stage router short-circuited
            # (always 0 colocated)
            "num_handoffs": self._num_handoffs,
            "num_handoff_requests": self._num_handoff_requests,
            "num_handoff_bytes": self._num_handoff_bytes,
            "num_affinity_probes_skipped":
                self._num_affinity_probes_skipped,
            # fleet-global shared prefix tier (docs/fleet.md, "Shared
            # prefix tier"): resident gauges, the publish/dedupe/hit
            # flow, eviction/refusal/corruption tallies and the scrub
            # coverage (all 0 with the tier off), plus the placement
            # hash-walk counter whose one-walk-per-decision bound the
            # regression test pins
            "shared_tier_blocks": (0 if self._shared is None
                                   else len(self._shared)),
            "shared_tier_bytes": (0 if self._shared is None
                                  else int(self._shared.total_bytes)),
            "shared_tier_hits": self._num_shared_hits,
            "num_shared_publishes": self._num_shared_publishes,
            "num_shared_dedupe": (0 if self._shared is None
                                  else int(self._shared.dedupe_hits)),
            "num_shared_evictions": (0 if self._shared is None
                                     else int(self._shared.evictions)),
            "num_shared_refused": (0 if self._shared is None
                                   else int(self._shared.refused)),
            "num_shared_corrupt_discards":
                (0 if self._shared is None
                 else int(self._shared.corrupt_discards)),
            "num_shared_scrub_blocks_verified":
                self._num_shared_scrub_blocks_verified,
            "num_hash_walks": self._num_hash_walks,
            "num_lost_requests": (self._num_accepted - len(self._owner)
                                  - self._num_terminal),
            "queue_depth": sum(rep.engine.queue_depth
                               for _, rep in alive),
            "active_slots": sum(rep.engine.active_slot_count
                                for _, rep in alive),
            "results_pending": len(self._results),
            "stream_backlog": len(self._stream),
            "replicas": reps,
            "tenants": self._tenant_section(tenant_rows),
        }

    def _tenant_section(self, tenant_rows) -> Dict[str, Dict[str, object]]:
        """One fleet-wide row per tenant: the per-replica ledger rows
        summed (tokens, waiting, residency, fractional charge, engine
        statuses), the router's own door tallies merged in, and the
        FLEET rate estimate (the number ``FleetConfig.tenant_quotas``'
        ``tokens_per_s`` is enforced against). With the shared prefix
        tier on, each tenant's ``shared_tier_bytes`` carries its
        fractional ownership charge (bytes split by publisher share —
        the shared-tier leg of the fractional block ledger) and a
        ``__shared__`` row carries the tier's resident total, so the
        per-tenant charges visibly sum to the tier."""
        agg: Dict[str, Dict[str, object]] = {}

        def row(t: str) -> Dict[str, object]:
            return agg.setdefault(t, {
                "tokens": 0, "waiting": 0, "resident_slots": 0,
                "resident_block_charge": 0.0,
                "shared_tier_bytes": 0.0,
                "rate_tokens_per_s": round(self._tenant_rate_now(t), 6),
                "statuses": {},
            })

        for rows in tenant_rows:
            for t, er in rows.items():
                r = row(t)
                r["tokens"] += er.get("tokens", 0)
                r["waiting"] += er.get("waiting", 0)
                r["resident_slots"] += er.get("resident_slots", 0)
                r["resident_block_charge"] = round(
                    r["resident_block_charge"]
                    + er.get("resident_block_charge", 0.0), 6)
                for s, c in (er.get("statuses") or {}).items():
                    r["statuses"][s] = r["statuses"].get(s, 0) + c
        if self._shared is not None:
            for t, b in self._shared.tenant_bytes().items():
                row(t)["shared_tier_bytes"] = b
            row("__shared__")["shared_tier_bytes"] = round(
                float(self._shared.total_bytes), 6)
        for t, tally in self._tenant_status.items():
            r = row(t)
            for s, c in tally.items():
                # the router's verdicts (fleet-door throttles, failover
                # quarantines, adopted checkpoints) — kept SEPARATE
                # from the engine tallies, which never saw them
                key = f"router_{s}"
                r["statuses"][key] = r["statuses"].get(key, 0) + c
        return agg


def _request_record(req: Request) -> Dict:
    """A fresh entry record from the router's own Request copy — the
    failover path for accepts the dead replica's checkpoint never saw.
    No ``arrival`` (the target assigns one), no generated tokens
    (nothing of it was delivered), deadline as its ORIGINAL budget
    (the router cannot know how much the dead replica burned; erring
    long keeps the request alive, and the target's gate/expiry still
    bound it)."""
    rec = {
        "uid": req.uid,
        "prompt": [int(t) for t in req.prompt],
        "max_new_tokens": int(req.max_new_tokens),
        "eos_token_id": (None if req.eos_token_id is None
                         else int(req.eos_token_id)),
        "sampling": {"temperature": float(req.sampling.temperature),
                     "top_k": int(req.sampling.top_k),
                     "top_p": float(req.sampling.top_p)},
        "priority": int(req.priority),
        "tenant": str(req.tenant),
        "generated": [],
        "drr_charged": False,
    }
    if req.deadline_s is not None:
        rec["deadline_remaining_s"] = float(req.deadline_s)
    return rec
