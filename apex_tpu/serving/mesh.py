"""GSPMD mesh layout for the inference engine (docs/serving.md,
"Mesh sharding").

The engine promotes from single-device to **mesh-native** through the
SNIPPETS.md [2] pattern: a logical 2-D device mesh with named axes
``("batch", "model")``, :class:`~jax.sharding.NamedSharding`
annotations on the weight and KV-pool tensors, and plain ``jax.jit`` —
the XLA SPMD partitioner inserts the collectives. Nothing about the
engine's host-side machinery (admission, DRR, quotas, the degradation
ladder, drafters, snapshot/spill/integrity) changes with the mesh:
block ids and SHA-256 chain hashes are layout-independent, so prefix
caching, the spill tier, and fleet migration records work unchanged at
any mesh shape.

What shards where (the full table lives in docs/serving.md):

- **KV pools** (``KVCache.k``/``v`` ``[L, N, bs, H, D]`` and the
  quantized ``k_scale``/``v_scale`` ``[L, N, bs, H]``): the head axis
  ``H`` splits over ``model`` (:meth:`KVCache.partition_specs`). Every
  paged scatter/gather/CoW/defrag op indexes only layer/block/slot
  axes, so pool maintenance never crosses the mesh.
- **GPT weights**: the Megatron decomposition via annotation — qkv and
  ``mlp_in`` kernels column-sharded (``P(None, "model")``, biases
  ``P("model")``), ``attn_out``/``mlp_out`` kernels row-sharded
  (``P("model", None)``), embeddings/layernorms replicated
  (:func:`~apex_tpu.models.gpt.gpt_param_pspec`). GSPMD then keeps
  activations head-sharded through attention and all-reduces the two
  row-parallel projections per block.
- **Everything else** — block tables, per-lane sampling arrays, PRNG
  keys, emitted tokens — is replicated: per-tick metadata is tiny, and
  replication is what keeps the sampler and the drain byte-identical
  across mesh shapes.

The ``batch`` axis is the DATA-PARALLEL lane split (docs/serving.md,
"The batch axis"): at ``mesh_shape=(B, M)`` with ``B > 1`` the engine
splits its ``max_batch`` decode lanes and the KV pools' BLOCK axis
into ``B`` contiguous shards, one per ``batch`` coordinate — so one
engine holds ``B`` times the concurrent residents of a ``(1, M)``
mesh at the same per-device pool footprint. The allocator pins every
sequence's blocks to its lane's shard, the sharded programs localize
the (global-id) block tables by subtracting the shard's base id
(foreign entries go out of bounds, where the scatter drops and the
gather reads masked garbage), and the per-lane sampler is already
schedule-invariant — which is why the split needs NO new collectives:
a ``(B, 1)`` mesh lowers collective-free like ``(1, 1)``, and a
``(B, M)`` mesh shows exactly the ``(1, M)`` model-axis reduction
traffic (:func:`expected_collectives` is per-shape).

**Identity contract**: mesh ``(1, 1)`` — the default — reproduces the
pre-mesh engine bit for bit (outputs, statuses, the full ``stats()``
dict; a 1-device SPMD partition is a no-op and the certification test
pins it), and :func:`expected_collectives` is the program-shape
contract ``hlo_audit`` checks: zero collectives at a 1-sized ``model``
axis, all-reduces (and nothing exotic) once the heads actually split.
``mesh_shape`` is part of the engine's restore-fingerprint identity
set: sharded snapshots restore across EQUAL meshes (the records
themselves are host-side and layout-free).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

MESH_AXES = ("batch", "model")


def validate_mesh_shape(mesh_shape, num_heads: Optional[int] = None,
                        knob: str = "mesh_shape",
                        max_batch: Optional[int] = None,
                        num_blocks: Optional[int] = None
                        ) -> Tuple[int, int]:
    """Validate (and normalize to a tuple) a ``(batch, model)`` mesh
    shape: two positive ints, a device footprint the backend can
    actually supply (checked lazily — the trivial ``(1, 1)`` never
    touches the backend, so constructing a default config cannot
    trigger plugin init), and — when the caller knows the model — a
    ``model``-axis size dividing ``num_heads`` (the KV pools and the
    qkv projections shard over heads; a non-dividing split has no
    layout). When the caller knows the engine geometry, the ``batch``
    axis must divide ``max_batch`` (lanes split into equal per-shard
    groups) and ``num_blocks`` (the pool splits into equal contiguous
    shard ranges). Named-knob errors, matching the config validation
    style."""
    try:
        shape = tuple(int(v) for v in mesh_shape)
        if any(s != v for s, v in zip(shape, mesh_shape)):
            raise ValueError   # non-integral axis (e.g. 1.5)
    except (TypeError, ValueError):
        raise ValueError(
            f"{knob} must be a (batch, model) pair of ints, "
            f"got {mesh_shape!r}")
    if len(shape) != 2:
        raise ValueError(
            f"{knob} must have exactly 2 axes (batch, model), "
            f"got {mesh_shape!r}")
    if any(v < 1 for v in shape):
        raise ValueError(
            f"{knob} axes must be >= 1, got {mesh_shape!r}")
    n = shape[0] * shape[1]
    if n > 1 and n > jax.device_count():
        raise ValueError(
            f"{knob} {shape} needs {n} devices but only "
            f"{jax.device_count()} are available "
            f"(CPU: XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    if num_heads is not None and num_heads % shape[1]:
        raise ValueError(
            f"{knob} model axis ({shape[1]}) must divide the model's "
            f"num_heads ({num_heads}): the KV pools and qkv projections "
            "shard over heads")
    if max_batch is not None and max_batch % shape[0]:
        raise ValueError(
            f"{knob} batch axis ({shape[0]}) must divide max_batch "
            f"({max_batch}): decode lanes split into equal per-shard "
            "groups")
    if num_blocks is not None and num_blocks % shape[0]:
        raise ValueError(
            f"{knob} batch axis ({shape[0]}) must divide num_blocks "
            f"({num_blocks}): the KV pool splits into equal contiguous "
            "shard ranges")
    return shape


def build_mesh(mesh_shape) -> Mesh:
    """The logical ``("batch", "model")`` device mesh for a validated
    shape — the first ``batch * model`` backend devices, row-major
    (deterministic, so equal shapes on equal processes build equal
    meshes and :class:`~jax.sharding.NamedSharding` keys compare
    equal across engine replicas)."""
    shape = validate_mesh_shape(mesh_shape)
    devices = np.asarray(jax.devices()[: shape[0] * shape[1]])
    return Mesh(devices.reshape(shape), MESH_AXES)


def replicated(mesh: Mesh) -> NamedSharding:
    """The fully-replicated sharding of ``mesh`` — every per-tick
    scalar/metadata tensor's layout."""
    return NamedSharding(mesh, PartitionSpec())


def cache_shardings(mesh: Mesh, cache):
    """``NamedSharding`` pytree for a :class:`~apex_tpu.serving.
    kv_cache.KVCache`: the pool's head axis over ``model``, and — once
    the ``batch`` axis is wider than 1 — the block axis over ``batch``
    (:meth:`KVCache.partition_specs` owns the spec layout; this binds
    it to a concrete mesh; a 1-wide batch axis keeps the exact
    pre-batch-axis spec, preserving the ``(1, 1)`` bit-identity
    certification). Also the ``out_shardings`` every jitted program
    pins its returned cache to — without the pin, GSPMD may hand back
    a differently-laid-out pool and the next dispatch's changed input
    sharding would recompile, breaking the one-program compile-count
    contract."""
    batch_axis = "batch" if mesh.shape["batch"] > 1 else None
    return jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                        cache.partition_specs(batch_axis=batch_axis))


def shard_cache(mesh: Mesh, cache):
    """Commit a KV pool to its mesh layout."""
    return jax.tree.map(jax.device_put, cache, cache_shardings(mesh, cache))


def shard_params(mesh: Mesh, params, pspec_fn=None):
    """Commit a param pytree to the mesh: each leaf device_put with the
    :class:`~jax.sharding.PartitionSpec` ``pspec_fn(path)`` names
    (default: the GPT layout,
    :func:`~apex_tpu.models.gpt.gpt_param_pspec` — a model with a
    different parameter tree supplies its own path->spec rule)."""
    if pspec_fn is None:
        from apex_tpu.models.gpt import gpt_param_pspec
        pspec_fn = gpt_param_pspec
    return jax.tree_util.tree_map_with_path(
        lambda path, x: jax.device_put(
            x, NamedSharding(mesh, pspec_fn(path))),
        params)


def program_out_shardings(mesh: Mesh, cache):
    """The ``(cache, tokens)`` output-sharding pair of the engine's
    prefill/decode/verify programs: the pool pinned to its mesh
    layout, emitted tokens replicated (the host drains them). With a
    sharded batch axis the tokens pin to ``P("batch")`` instead —
    each shard computed only its own lanes' tokens, and replicating
    them would force the partitioner to insert an all-gather into the
    decode program (breaking the batch axis's no-new-collectives
    contract); the host's fetch assembles the shards. Returned as a
    2-tuple the engine threads into ``jax.jit(out_shardings=...)``
    (cache-only programs — CoW copy, spill upload — use element 0)."""
    if mesh.shape["batch"] > 1:
        tokens = NamedSharding(mesh, PartitionSpec("batch"))
    else:
        tokens = replicated(mesh)
    return cache_shardings(mesh, cache), tokens


def expected_collectives(mesh_shape) -> dict:
    """The sharded program-shape contract for
    :func:`apex_tpu.utils.hlo_audit.assert_collective_contract`, per
    shape over BOTH axes. The ``batch`` axis contributes NOTHING at
    any shape — shards hold disjoint lanes and disjoint pool ranges,
    tables localize by subtraction, and token outputs stay
    batch-sharded, so there is no cross-shard data motion to lower:

    - ``model == 1`` (including every ``(B, 1)`` batch split): every
      program must lower with ZERO collectives — the bit-identity
      certification at ``(1, 1)`` and the batch axis's
      no-new-collectives contract at ``(B, 1)`` both lean on this.
    - ``model > 1`` (``(1, M)`` and the combined ``(B, M)``): the
      Megatron-via-GSPMD layout must show cross-partition reduction
      traffic (all-reduce, or the reduce-scatter + all-gather pair XLA
      sometimes splits one into) and must NOT show all-to-all (a
      resharding of the sequence or head axis this layout never asks
      for — its appearance means the partitioner lost the intended
      layout somewhere, and at ``B > 1`` it is exactly what a leaked
      cross-shard lane or pool index would look like)."""
    shape = validate_mesh_shape(mesh_shape)
    if shape[1] == 1:
        return {"exact_total_ops": 0}
    return {
        "min_ops": {"all-reduce": 1},
        "alt_min_ops": {"reduce-scatter": 1, "all-gather": 1},
        "forbidden": ("all-to-all",),
    }


def train_expected_collectives(mesh_shape, num_layers: Optional[int] = None,
                               zero: bool = False) -> dict:
    """The sharded TRAIN-step program-shape contract
    (``TrainStep.audit_collectives`` feeds this to
    :func:`apex_tpu.utils.hlo_audit.assert_collective_contract`),
    per ``(batch, model)`` shape:

    - ``(1, 1)``: exactly ZERO collectives — the bit-identity
      certification against the meshless fused step leans on a
      1-device SPMD partition being a no-op, same as serving.
    - ``batch > 1`` with a ZeRO flat optimizer (``zero=True``): the
      reduce leg must show the one-reduce-scatter + one-all-gather
      ZeRO round trip — or the all-reduce + all-gather spelling
      XLA:CPU lowers the same reduction to (``alt_min_ops``, the
      round-5 equivalence rule).
    - ``batch > 1`` without ZeRO: at least the one post-scan gradient
      all-reduce over the batch axis.
    - ``model > 1``: the Megatron TP leg — GSPMD all-reduces the two
      row-parallel projections per block, forward and backward, so the
      floor is ``2 * num_layers`` all-reduces (1 when the layer count
      is unknown).
    - always: NO all-to-all — this layout never reshards an axis, and
      on the train side an all-to-all is exactly what the flattened
      ZeRO stream looks like when the partitioner loses the
      replicate-before-flatten constraint.
    """
    shape = validate_mesh_shape(mesh_shape)
    batch, model = shape
    if batch == 1 and model == 1:
        return {"exact_total_ops": 0}
    min_ops = {}
    if model > 1:
        min_ops["all-reduce"] = 2 * num_layers if num_layers else 1
    if batch > 1 and zero:
        rs = dict(min_ops)
        rs["reduce-scatter"] = rs.get("reduce-scatter", 0) + 1
        rs["all-gather"] = rs.get("all-gather", 0) + 1
        alt = dict(min_ops)
        alt["all-reduce"] = alt.get("all-reduce", 0) + 1
        alt["all-gather"] = alt.get("all-gather", 0) + 1
        return {"min_ops": rs, "alt_min_ops": alt,
                "forbidden": ("all-to-all",)}
    if batch > 1:
        min_ops["all-reduce"] = min_ops.get("all-reduce", 0) + 1
    return {"min_ops": min_ops, "forbidden": ("all-to-all",)}
