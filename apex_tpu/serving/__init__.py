"""apex_tpu.serving — the inference leg of the stack.

Paged KV-cache (:mod:`~apex_tpu.serving.kv_cache`), continuous-batching
prefill/decode engine (:mod:`~apex_tpu.serving.engine`), jit-stable
sampling (:mod:`~apex_tpu.serving.sampling`), the GSPMD mesh layout
that shards an engine over a ``("batch", "model")`` device mesh
(:mod:`~apex_tpu.serving.mesh`), the crash-tolerant
multi-replica fleet router (:mod:`~apex_tpu.serving.fleet`), and the
out-of-process replica runtime — the framed stdio RPC layer
(:mod:`~apex_tpu.serving.wire`), the parent-side child handle
(:mod:`~apex_tpu.serving.process_replica`), and the child entrypoint
(:mod:`~apex_tpu.serving.replica_worker`); design
notes in docs/serving.md and docs/fleet.md. The training-side capability surface (amp dtype
policy, the flash-attention kernel family, the GPT/BERT models) is
reused, not duplicated: the cache stores in the amp compute dtype, the
decode path lives in :mod:`apex_tpu.ops.flash_attention`, and the model
hook is ``GPTLMHeadModel.apply(..., kv_cache=...)``.
"""

from apex_tpu.serving.drafter import (  # noqa: F401
    Drafter,
    GPTDrafter,
    NgramDrafter,
)
from apex_tpu.serving.engine import (  # noqa: F401
    EngineConfig,
    EngineStalledError,
    InferenceEngine,
    QueueFullError,
    Request,
    RequestResult,
    TenantQuota,
    TenantThrottledError,
)
from apex_tpu.serving.fleet import (  # noqa: F401
    FleetConfig,
    FleetFailedError,
    FleetRouter,
)
from apex_tpu.serving.process_replica import (  # noqa: F401
    ProcessReplica,
    RemoteEngineError,
    ReplicaUnavailableError,
    gpt_model_spec,
    params_checksum,
)
from apex_tpu.serving.mesh import (  # noqa: F401
    MESH_AXES,
    build_mesh,
    expected_collectives,
    shard_cache,
    shard_params,
    validate_mesh_shape,
)
from apex_tpu.serving.kv_cache import (  # noqa: F401
    DEFAULT_TENANT,
    KV_QUANT_MODES,
    BlockAllocator,
    CacheOutOfBlocks,
    DeviceMirror,
    HostSpillStore,
    KVCache,
    SharedPrefixStore,
    blocks_needed,
    copy_block,
    default_kv_dtype,
    defragment,
    device_block_table,
    gather_blocks,
    gather_kv,
    hash_block_tokens,
    kv_block_bytes,
    paged_write,
    quantize_kv_rows,
    seq_block_hashes,
    write_kv,
)
from apex_tpu.serving.sampling import (  # noqa: F401
    SamplingParams,
    sample_tokens,
    sample_tokens_per_lane,
    spec_verify_tokens,
)
from apex_tpu.utils.integrity import IntegrityError  # noqa: F401
