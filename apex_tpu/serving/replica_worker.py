"""The child-process replica worker (docs/fleet.md, "Process
replicas"): ``python -m apex_tpu.serving.replica_worker``.

Spawned by :class:`~apex_tpu.serving.process_replica.ProcessReplica`
with the frame protocol on stdio. Boot sequence: read ONE ``init``
frame (engine config record, model spec, expected params checksum,
optional serialized fault plan and clock spec), rebuild the model from
the spec, PROVE the weights match the parent's
(:func:`~apex_tpu.serving.process_replica.params_checksum` — a
mismatched spec is refused at the handshake, never served), construct
the :class:`~apex_tpu.serving.engine.InferenceEngine`, and answer a
``hello``. Then a strictly serial request/response loop: one ``call``
frame in, one ``resp`` frame out, in order — the parent is the only
client, so there is no concurrency to manage, and lockstep is what
makes the retry protocol sound.

Worker-side guarantees:

- **fd hygiene**: stdin/stdout are ``dup``'d for frames and real
  stdout is re-pointed at stderr FIRST, so a stray ``print`` (jax
  warnings, user hooks) can never tear a frame;
- **at-most-once**: the response to the most recent id is cached; a
  duplicate id (the parent resending after a torn response) is
  answered from the cache WITHOUT re-executing, so a retried
  ``add_request``/``import_requests`` never double-applies;
- **engine errors do not kill the worker**: they serialize into the
  ``resp`` as typed error records (the parent re-raises the real
  ``QueueFullError``/``TenantThrottledError``/``ValueError``/
  ``IntegrityError``) and the loop continues;
- **torn requests are reported, not fatal**: an ``IntegrityError``
  reading a frame answers with an id-less error frame — the parent
  resends under the same id;
- **checkpoints piggyback**: whenever the engine's periodic
  ``last_checkpoint`` refreshes, the next ``step`` response carries
  the sealed snapshot, keeping the parent's failover cache at
  bounded staleness without extra round trips;
- **exit**: a clean parent close (``WireClosedError``) or a
  ``shutdown`` frame ends the process; SIGKILL needs no cooperation,
  which is the point of the chaos cert.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Tuple

from apex_tpu.serving import wire
from apex_tpu.utils.integrity import IntegrityError


def _error_record(e: BaseException) -> Dict:
    rec = {"type": type(e).__name__, "message": str(e)}
    if isinstance(e, IntegrityError):
        rec["site"] = e.site
        rec["detail"] = e.detail
    return rec


class _Servicer:
    """Method dispatch + argument/result codecs around one live
    engine (the worker-side mirror of ``ProcessReplica._call``)."""

    def __init__(self, engine):
        self.engine = engine
        # identity of the last checkpoint already shipped to the
        # parent — piggybacking keys on it, not on tick counts
        self._ckpt_sent = None

    def dispatch(self, method: str, args: List) -> Tuple[object, Dict]:
        """``(result, extra_response_fields)`` for one RPC."""
        from apex_tpu.serving.process_replica import request_from_record

        eng = self.engine
        if method == "add_request":
            return int(eng.add_request(request_from_record(args[0]))), {}
        if method == "step":
            busy = bool(eng.step())
            extra: Dict = {}
            snap = eng.last_checkpoint
            if snap is not None and id(snap) != self._ckpt_sent:
                extra["checkpoint"] = snap
                self._ckpt_sent = id(snap)
            return busy, extra
        if method == "has_work":
            return bool(eng.has_work), {}
        if method == "load":
            return eng.load(), {}
        if method == "probe_prefix":
            return int(eng.probe_prefix(list(args[0]))), {}
        if method == "spilled_hashes":
            return {str(h): str(t)
                    for h, t in eng.spilled_hashes().items()}, {}
        if method == "decoding_uids":
            return [str(u) for u in eng.decoding_uids()], {}
        if method == "exported_arrival":
            return eng.exported_arrival(str(args[0])), {}
        if method == "drop_stream_events":
            return int(eng.drop_stream_events(str(args[0]))), {}
        if method == "export_requests":
            uids = args[0] if args else None
            return eng.export_requests(uids), {}
        if method == "import_requests":
            return int(eng.import_requests(args[0])), {}
        if method == "pop_results":
            return {uid: {"tokens": [int(t) for t in res.tokens],
                          "status": res.status}
                    for uid, res in eng.pop_results().items()}, {}
        if method == "pop_stream_events":
            return [[uid, int(tok), bool(last)]
                    for uid, tok, last in eng.pop_stream_events()], {}
        if method == "abort":
            return bool(eng.abort(args[0])), {}
        if method == "checkpoint":
            snap = eng.checkpoint()
            self._ckpt_sent = id(snap)
            return snap, {}
        if method == "export_prefix_payloads":
            return wire.encode_arrays(
                eng.export_prefix_payloads(list(args[0]))), {}
        if method == "import_prefix_payloads":
            return int(eng.import_prefix_payloads(
                wire.decode_arrays(args[0]))), {}
        if method == "stats":
            import json

            # one normalization pass (tuples -> lists, the odd
            # non-JSON scalar -> str) so the frame encoder never
            # chokes on a stats leaf
            return json.loads(json.dumps(eng.stats(), default=str)), {}
        if method == "block_weight":
            return float(eng.block_weight), {}
        if method == "queue_depth":
            return int(eng.queue_depth), {}
        if method == "active_slot_count":
            return int(eng.active_slot_count), {}
        if method == "tenant_charge":
            return eng.tenant_charge(args[0]), {}
        if method == "tenant_depth":
            return int(eng.tenant_depth(args[0])), {}
        raise ValueError(f"unknown RPC method {method!r}")


def _boot(init: Dict):
    """Model + engine from the init frame; raises on any mismatch
    (the caller turns it into a refused hello)."""
    from apex_tpu.serving.engine import InferenceEngine
    from apex_tpu.serving.process_replica import (
        build_model_from_spec,
        clock_from_spec,
        engine_config_from_record,
        params_checksum,
    )
    from apex_tpu.utils.faults import plan_from_record

    config = engine_config_from_record(init["config"])
    model, params = build_model_from_spec(init["model_spec"])
    expect = init.get("params_checksum")
    if expect is not None:
        # hash the representation this child will serve: under
        # weight_quantization the checksum covers the quantized tree
        # + the mode tag, so a child booted with a mismatched mode
        # (or a spec that doesn't reproduce the weights) is refused
        got = params_checksum(
            params, weight_quantization=config.weight_quantization)
        if got != expect:
            raise IntegrityError(
                "wire", f"child-rebuilt params checksum {got} != "
                        f"parent's {expect}: the model spec does not "
                        "reproduce the parent's weights (or the "
                        "weight_quantization mode does not match)")
    plan_rec = init.get("faults")
    faults = None if plan_rec is None else plan_from_record(plan_rec)
    clock = clock_from_spec(init.get("clock"))
    return InferenceEngine(model, params, config, faults=faults,
                           clock=clock)


def main() -> int:
    # fd hygiene FIRST: frames own private dups of stdin/stdout, and
    # fd 1 is re-pointed at stderr so any stray print lands in the
    # parent's stderr stream instead of inside a frame
    in_fd = os.dup(0)
    out_fd = os.dup(1)
    os.dup2(2, 1)
    sys.stdout = sys.stderr

    try:
        init = wire.read_frame(in_fd)
        if init.get("type") != "init":
            raise ValueError(
                f"expected an init frame, got {init.get('type')!r}")
        servicer = _Servicer(_boot(init))
    except wire.WireClosedError:
        return 0
    except BaseException as e:  # noqa: BLE001 - refused hello carries it
        try:
            wire.write_frame(out_fd, {"type": "hello", "ok": False,
                                      "error": _error_record(e)})
        except Exception:
            pass
        return 1
    wire.write_frame(out_fd, {"type": "hello", "ok": True,
                              "pid": os.getpid()})

    last_id = None
    last_resp = None
    while True:
        try:
            msg = wire.read_frame(in_fd)
        except wire.WireClosedError:
            return 0
        except IntegrityError as e:
            # a torn REQUEST: report without an id; the parent resends
            wire.write_frame(out_fd, {"type": "resp", "id": None,
                                      "ok": False,
                                      "error": _error_record(e)})
            continue
        mtype = msg.get("type")
        if mtype == "shutdown":
            wire.write_frame(out_fd, {"type": "resp",
                                      "id": msg.get("id"),
                                      "ok": True, "result": None})
            return 0
        if mtype != "call":
            wire.write_frame(out_fd, {"type": "resp", "id": None,
                                      "ok": False,
                                      "error": {"type": "ValueError",
                                                "message": f"unexpected "
                                                f"frame type {mtype!r}"}})
            continue
        mid = msg.get("id")
        if mid is not None and mid == last_id:
            # at-most-once: the parent resent after a torn response —
            # answer from the cache, never re-execute
            wire.write_frame(out_fd, last_resp)
            continue
        try:
            result, extra = servicer.dispatch(msg.get("method"),
                                              msg.get("args") or [])
            resp = {"type": "resp", "id": mid, "ok": True,
                    "result": result}
            resp.update(extra)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 - typed error resp
            resp = {"type": "resp", "id": mid, "ok": False,
                    "error": _error_record(e)}
        last_id, last_resp = mid, resp
        wire.write_frame(out_fd, resp)


if __name__ == "__main__":
    sys.exit(main())
