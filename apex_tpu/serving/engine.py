"""Continuous-batching inference engine: prefill/decode split over the
paged KV-cache, with a fixed-shape scheduler.

The Orca/vLLM serving loop (PAPERS.md) restated for XLA, where a shape
change means a recompile and a recompile means a multi-second stall
mid-traffic. The engine therefore holds a **two-program contract**:

- ``prefill``: one request at a time at the fixed shape
  ``[1, max_prefill_len]`` — prompt tokens right-padded, causal
  attention with the padding key-masked, K/V written into freshly
  allocated cache blocks, and the FIRST generated token sampled from
  the last real position's logits.
- ``decode``: ALL active slots at once at the fixed shape
  ``[max_batch, 1]`` — each slot's last token attends against its block
  table, one token sampled per slot. Inactive slots ride along as
  masked lanes (their block-table rows point out of bounds, so their
  writes drop and their outputs are ignored).

Everything that varies between steps — which slots are live, block
tables, context lengths, sampling knobs — varies as *array values*, so
XLA compiles exactly two programs for the lifetime of the engine
(``stats()["prefill_compilations"] == 1`` and likewise for decode; the
acceptance test pins this).

Scheduling (host-side, between jitted steps): admission fills free
decode slots from the FIFO waiting queue whenever the request's
WORST-CASE block count (prompt + full ``max_new_tokens`` budget) fits
in the free pool net of what already-active slots may still claim
(continuous batching — new requests join mid-flight, nothing waits for
a "batch" to form); eviction frees a slot's blocks the moment it
finishes (EOS sampled, or ``max_new_tokens`` reached). The worst-case
reservation guarantees a decode-time block allocation can never fail;
preemption/swapping (which would allow optimistic admission) is future
work.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.serving.kv_cache import (
    BlockAllocator,
    CacheOutOfBlocks,
    KVCache,
    blocks_needed,
    device_block_table,
)
from apex_tpu.serving.sampling import SamplingParams, sample_tokens


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request. ``prompt`` is a token-id sequence;
    generation runs until EOS (if ``eos_token_id`` is set) or
    ``max_new_tokens``, whichever comes first."""

    uid: str
    prompt: Sequence[int]
    max_new_tokens: int = 16
    sampling: SamplingParams = SamplingParams()
    eos_token_id: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    max_batch: int = 8            # decode slots
    block_size: int = 16
    num_blocks: int = 256         # pool size (per layer)
    max_prefill_len: int = 64     # THE prefill shape; prompts must fit
    max_seq_len: int = 256        # prompt + generation cap per sequence
    kv_dtype: Optional[object] = None   # None = follow the amp policy
    # Donate the cache pool to the jitted steps so XLA updates it in
    # place instead of materializing a second pool + copy per step
    # (double peak HBM and a full-pool write otherwise). Default off:
    # the axon TPU runtime rejects donated buffers at run time (see
    # bench.py's --donate probe history) and older CPU jaxlibs ignore
    # donation with a warning; flip on for runtimes that support it.
    donate_cache: bool = False
    seed: int = 0


@dataclasses.dataclass
class _Slot:
    """Host-side state of one active decode lane."""

    request: Request
    context_len: int              # tokens currently in the cache
    blocks: List[int]             # owned block ids, sequence order
    generated: List[int]
    last_token: int


class InferenceEngine:
    """Drives a :class:`~apex_tpu.models.gpt.GPTLMHeadModel` (or any
    model exposing the same ``kv_cache=`` apply contract) through
    continuous-batching generation.

    Usage::

        engine = InferenceEngine(model, params, EngineConfig(...))
        engine.add_request(Request("a", prompt, max_new_tokens=32))
        outputs = engine.run()          # {"a": [tok, tok, ...]}

    ``add_request`` may be called at any time, including between
    ``step()`` calls while other requests are mid-generation — that is
    the continuous-batching point.
    """

    def __init__(self, model, params, config: EngineConfig):
        cfg = model.cfg
        self.model = model
        self.params = params
        self.config = config
        if config.max_prefill_len > config.max_seq_len:
            raise ValueError("max_prefill_len exceeds max_seq_len")
        if config.max_seq_len > cfg.max_position_embeddings:
            raise ValueError(
                f"max_seq_len ({config.max_seq_len}) exceeds the model's "
                f"max_position_embeddings ({cfg.max_position_embeddings})")
        self.max_blocks_per_seq = blocks_needed(config.max_seq_len,
                                                config.block_size)
        self.cache = KVCache.create(
            cfg.num_layers, config.num_blocks, config.block_size,
            cfg.num_heads, cfg.hidden_size // cfg.num_heads,
            dtype=config.kv_dtype)
        self.allocator = BlockAllocator(config.num_blocks)
        self.slots: List[Optional[_Slot]] = [None] * config.max_batch
        self.waiting: deque = deque()
        self.finished: Dict[str, List[int]] = {}
        self._key = jax.random.PRNGKey(config.seed)
        self._step_count = 0
        self._num_prefills = 0
        self._num_decode_steps = 0
        # the two programs; anything else jitted here would break the
        # two-compilation contract the tests pin. Arg 1 is the cache
        # pool in both signatures (donated when the runtime allows).
        donate = (1,) if config.donate_cache else ()
        self._prefill = jax.jit(self._prefill_impl, donate_argnums=donate)
        self._decode = jax.jit(self._decode_impl, donate_argnums=donate)

    # -- the two jitted programs ------------------------------------------

    def _prefill_impl(self, params, cache, ids, seq_len, table, key,
                      temp, top_k, top_p):
        P = ids.shape[1]
        positions = jnp.arange(P, dtype=jnp.int32)[None]
        logits, cache = self.model.apply(
            params, ids, deterministic=True, kv_cache=cache,
            block_tables=table, cache_positions=positions,
            seq_lens=seq_len)
        last = jnp.take_along_axis(
            logits, (seq_len - 1)[:, None, None], axis=1)[:, 0]  # [1, V]
        tok = sample_tokens(last, key, temp, top_k, top_p)
        return cache, tok

    def _decode_impl(self, params, cache, tokens, tables, context_lens,
                     key, temp, top_k, top_p):
        logits, cache = self.model.apply(
            params, tokens, deterministic=True, kv_cache=cache,
            block_tables=tables,
            cache_positions=context_lens[:, None],
            seq_lens=context_lens + 1)
        tok = sample_tokens(logits[:, 0], key, temp, top_k, top_p)
        return cache, tok

    # -- host-side scheduling ---------------------------------------------

    def add_request(self, request: Request) -> None:
        n = len(request.prompt)
        if n == 0:
            raise ValueError(f"request {request.uid!r}: empty prompt")
        if request.max_new_tokens < 1:
            raise ValueError(
                f"request {request.uid!r}: max_new_tokens must be >= 1 "
                f"(got {request.max_new_tokens}); prefill always samples "
                "the first token")
        if n > self.config.max_prefill_len:
            raise ValueError(
                f"request {request.uid!r}: prompt length {n} exceeds "
                f"max_prefill_len ({self.config.max_prefill_len})")
        if n + request.max_new_tokens > self.config.max_seq_len:
            raise ValueError(
                f"request {request.uid!r}: prompt + max_new_tokens "
                f"({n} + {request.max_new_tokens}) exceeds max_seq_len "
                f"({self.config.max_seq_len})")
        request.sampling.validate()
        self.waiting.append(request)

    def _next_key(self):
        self._step_count += 1
        return jax.random.fold_in(self._key, self._step_count)

    def _host_tables(self) -> np.ndarray:
        t = np.full((self.config.max_batch, self.max_blocks_per_seq), -1,
                    np.int32)
        for i, slot in enumerate(self.slots):
            if slot is not None:
                t[i, : len(slot.blocks)] = slot.blocks
        return t

    def _sampling_arrays(self, per_slot):
        temp = np.zeros(len(per_slot), np.float32)
        top_k = np.zeros(len(per_slot), np.int32)
        top_p = np.ones(len(per_slot), np.float32)
        for i, sp in enumerate(per_slot):
            if sp is not None:
                temp[i], top_k[i], top_p[i] = (sp.temperature, sp.top_k,
                                               sp.top_p)
        return (jnp.asarray(temp), jnp.asarray(top_k), jnp.asarray(top_p))

    def _finish(self, idx: int) -> None:
        slot = self.slots[idx]
        self.allocator.free(slot.blocks)
        self.finished[slot.request.uid] = slot.generated
        self.slots[idx] = None

    def _record_token(self, idx: int, token: int) -> None:
        """Append a sampled token to a slot, finishing on EOS/max-len."""
        slot = self.slots[idx]
        slot.generated.append(token)
        slot.last_token = token
        req = slot.request
        if ((req.eos_token_id is not None and token == req.eos_token_id)
                or len(slot.generated) >= req.max_new_tokens):
            self._finish(idx)

    def _worst_case_blocks(self, req: Request) -> int:
        return blocks_needed(len(req.prompt) + req.max_new_tokens,
                             self.config.block_size)

    def _reserved_outstanding(self) -> int:
        """Blocks the ACTIVE slots may still allocate before finishing
        (their worst case minus what they already own). Admission
        reserves against this so a decode-time ``alloc`` can never
        fail — without preemption, over-commit would abort every
        in-flight generation mid-step."""
        total = 0
        for s in self.slots:
            if s is not None:
                total += max(0, self._worst_case_blocks(s.request)
                             - len(s.blocks))
        return total

    def _admit(self) -> int:
        """Move waiting requests into free slots while capacity lasts:
        the request's WORST-CASE block count (prompt + full generation
        budget) must fit in the unreserved free pool. Returns the
        number of requests admitted (a prefilled request may FINISH
        during admission — max_new_tokens=1, or EOS on the first
        sampled token — so progress cannot be read off the slots)."""
        admitted = 0
        for idx in range(self.config.max_batch):
            if not self.waiting or self.slots[idx] is not None:
                continue
            req = self.waiting[0]
            free_unreserved = (self.allocator.num_free
                               - self._reserved_outstanding())
            if self._worst_case_blocks(req) > free_unreserved:
                break   # FIFO: don't let a small request starve the head
            need = blocks_needed(len(req.prompt), self.config.block_size)
            self.waiting.popleft()
            blocks = self.allocator.alloc(need)
            n = len(req.prompt)
            P = self.config.max_prefill_len
            ids = np.zeros((1, P), np.int32)
            ids[0, :n] = np.asarray(req.prompt, np.int32)
            table = np.full((1, self.max_blocks_per_seq), -1, np.int32)
            table[0, : len(blocks)] = blocks
            temp, top_k, top_p = self._sampling_arrays([req.sampling])
            self.cache, tok = self._prefill(
                self.params, self.cache, jnp.asarray(ids),
                jnp.asarray([n], jnp.int32),
                device_block_table(table, self.config.num_blocks),
                self._next_key(), temp, top_k, top_p)
            self._num_prefills += 1
            self.slots[idx] = _Slot(request=req, context_len=n,
                                    blocks=blocks, generated=[],
                                    last_token=0)
            self._record_token(idx, int(tok[0]))
            admitted += 1
        return admitted

    def _ensure_decode_blocks(self) -> None:
        """Each active slot is about to write K/V at position
        ``context_len`` — allocate that block if the table doesn't
        cover it yet."""
        for slot in self.slots:
            if slot is None:
                continue
            need = blocks_needed(slot.context_len + 1,
                                 self.config.block_size)
            while len(slot.blocks) < need:
                slot.blocks.extend(self.allocator.alloc(1))

    def step(self) -> None:
        """One scheduler tick: admit, then one decode step for every
        active slot (if any)."""
        admitted = self._admit()
        active = [i for i, s in enumerate(self.slots) if s is not None]
        if not active:
            if self.waiting and not admitted:
                # zero live sequences means nothing will ever free a
                # block — the queue head can never be admitted (the
                # pool is undersized for it). Raise, don't spin.
                req = self.waiting[0]
                raise CacheOutOfBlocks(
                    f"request {req.uid!r} needs "
                    f"{self._worst_case_blocks(req)} blocks worst-case "
                    f"but only {self.allocator.num_free} of "
                    f"{self.allocator.num_blocks} can ever be free")
            return
        self._ensure_decode_blocks()
        B = self.config.max_batch
        tokens = np.zeros((B, 1), np.int32)
        ctx = np.zeros((B,), np.int32)
        for i in active:
            tokens[i, 0] = self.slots[i].last_token
            ctx[i] = self.slots[i].context_len
        temp, top_k, top_p = self._sampling_arrays(
            [s.request.sampling if s is not None else None
             for s in self.slots])
        self.cache, toks = self._decode(
            self.params, self.cache, jnp.asarray(tokens),
            device_block_table(self._host_tables(),
                               self.config.num_blocks),
            jnp.asarray(ctx), self._next_key(), temp, top_k, top_p)
        self._num_decode_steps += 1
        toks = np.asarray(toks)
        for i in active:
            self.slots[i].context_len += 1
            self._record_token(i, int(toks[i]))

    def run(self) -> Dict[str, List[int]]:
        """Drain: step until every queued and active request finishes.
        Returns ``{uid: generated_token_ids}``."""
        while self.waiting or any(s is not None for s in self.slots):
            self.step()
        out, self.finished = self.finished, {}
        return out

    def stats(self) -> Dict[str, float]:
        return {
            "prefill_compilations": self._prefill._cache_size(),
            "decode_compilations": self._decode._cache_size(),
            "num_prefills": self._num_prefills,
            "num_decode_steps": self._num_decode_steps,
            "active_slots": sum(s is not None for s in self.slots),
            "waiting": len(self.waiting),
            "cache_utilization": self.allocator.utilization,
        }
