"""Continuous-batching inference engine: chunked prefill + multi-step
fused decode over the paged KV-cache, with a fixed-shape scheduler,
prefix caching, and optimistic admission backed by preemption.

The Orca/vLLM serving loop (PAPERS.md) restated for XLA, where a shape
change means a recompile and a recompile means a multi-second stall
mid-traffic. The engine therefore holds a **fixed-program contract**:

- ``prefill``: one request at a time at the fixed shape
  ``[1, prefill_chunk]``, iterated over the prompt — each chunk's K/V
  are scattered into the sequence's cache blocks, then the chunk's
  queries attend against EVERYTHING cached so far (matched prefix
  blocks, earlier chunks, the chunk itself) through the block table
  (Sarathi-style chunked prefill: a long prompt no longer head-of-line
  blocks the decode slots, and prompts up to ``max_seq_len`` are
  admissible regardless of the chunk size). The FIRST generated token
  is sampled from the last real position's logits of the final chunk.
- ``decode``: ALL slots at once, ``decode_steps`` (K) iterations fused
  into ONE dispatch via ``jax.lax.scan`` — each inner step writes the
  previous token's K/V through the block table, attends, samples one
  token per lane (per-lane PRNG keys, see below), advances per-lane
  context lengths on-device, and feeds the token back as the next
  query. A per-lane active mask freezes lanes that hit EOS or their
  ``max_new_tokens`` budget mid-scan: frozen lanes stop writing
  (``write_start`` pushes their scatter out of the valid range) and
  emit a ``-1`` sentinel. The program returns ``[max_batch, K]`` tokens
  (``-1`` sentinels past each lane's emitted prefix), and the host
  fetch is DEFERRED: the next tick's admission and prefill work is
  dispatched before the host blocks on the in-flight decode, so
  scheduler overhead overlaps device compute. ``K == 1`` runs the same
  single-token computation and scheduling cadence as the pre-multistep
  engine (greedy outputs are unchanged; sampled draws come from the
  rekeyed per-request scheme below, which intentionally replaced the
  old step-counter keys at every K). Non-decoding lanes (empty, or
  still prefilling) ride along masked (their table rows point out of
  bounds, so their writes drop and their outputs are ignored).
- ``cow copy`` (rare): one block duplicated when a sequence would
  append into a block it shares with another sequence — compiled
  lazily, only if copy-on-write ever triggers.

Everything that varies between steps — which slots are live, block
tables, chunk offsets, context lengths, sampling knobs — varies as
*array values*, so XLA compiles one program per shape for the lifetime
of the engine (``stats()["prefill_compilations"] == 1`` and likewise
for decode; the acceptance tests pin this). The block table and the
per-lane sampling/EOS/key arrays are **dirty-tracked device-resident
mirrors** (:class:`~apex_tpu.serving.kv_cache.DeviceMirror`):
re-uploaded when the slot composition or a table row changes, reused
untouched on the steady-state tick.

Sampling determinism is **schedule-invariant**: every request owns a
PRNG key (the engine seed folded with the request's arrival index),
and its ``j``-th generated token is drawn with
``fold_in(request_key, j)`` — on-device, the scan folds the running
per-lane generated-count into the lane's key each iteration. Outputs
are therefore bit-for-bit identical for any ``decode_steps``, any lane
placement, and any preemption/resume schedule (tested).

Scheduling (host-side, between jitted dispatches), per ``step()``:

1. **Admission** fills free decode slots from the FIFO waiting queue
   on *current* need, not worst case: the prompt's uncached tail blocks
   plus one must fit in the pool (free + evictable). With prefix
   caching enabled, the longest block-aligned cached prefix is matched
   by content hash and shared (refcounted) instead of recomputed.
2. **One prefill chunk** runs for the oldest admitted request still
   mid-prompt — at most one chunk per step ahead of the decode
   dispatch, so decode slots keep streaming tokens while a long prompt
   loads (stall-free batching).
3. **Drain** the PREVIOUS tick's decode dispatch (the deferred sync):
   fetch its ``[B, K]`` tokens + counts, append K/V bookkeeping,
   register newly-full blocks, finish/evict satisfied requests, then
   top up admissions into any lanes that just freed.
4. **Decode** dispatches the next fused K-step scan for every started
   slot. When a K-step block reservation fails, the YOUNGEST slot is
   preempted: its references are released and the request re-queued at
   the front carrying its already-generated tokens — on re-admission
   it re-prefills ``prompt + generated[:-1]`` (cheap under prefix
   caching: its own blocks are usually still cached) and continues, so
   emitted tokens are never resampled and per-request output is
   deterministic. Preemption granularity is K tokens: a preempted lane
   loses at most the current dispatch's unconsumed reservation, never
   an emitted token.

Finished requests *release references* instead of freeing: with prefix
caching on, their full blocks stay indexed and evictable (LRU) until
the pool actually needs the space.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.serving.kv_cache import (
    BlockAllocator,
    CacheOutOfBlocks,
    DeviceMirror,
    KVCache,
    blocks_needed,
    copy_block,
    device_block_table,
    hash_block_tokens,
)
from apex_tpu.serving.sampling import (
    SamplingParams,
    sample_tokens,
    sample_tokens_per_lane,
)


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request. ``prompt`` is a token-id sequence;
    generation runs until EOS (if ``eos_token_id`` is set) or
    ``max_new_tokens``, whichever comes first."""

    uid: str
    prompt: Sequence[int]
    max_new_tokens: int = 16
    sampling: SamplingParams = SamplingParams()
    eos_token_id: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    max_batch: int = 8            # decode slots
    block_size: int = 16
    num_blocks: int = 256         # pool size (per layer)
    max_prefill_len: int = 64     # default prefill chunk (see below)
    max_seq_len: int = 256        # prompt + generation cap per sequence
    # THE prefill shape: prompts are prefilled in [1, prefill_chunk]
    # pieces, so prompts up to max_seq_len are admissible regardless of
    # the chunk. None inherits max_prefill_len (the pre-chunking shape,
    # keeping existing configs' compiled footprint identical).
    prefill_chunk: Optional[int] = None
    # Multi-step fused decode: each decode dispatch runs this many
    # scanned iterations on-device, amortizing one scheduler tick (host
    # table/array work + dispatch + fetch) over K generated tokens.
    # Outputs are bit-identical for any K (per-request, per-token PRNG
    # keys); K trades per-token latency (tokens surface K at a time)
    # for throughput, and makes K tokens the preemption granularity.
    # 1 keeps the pre-multistep single-token cadence (sampled draws
    # use the rekeyed per-request scheme at every K, including 1).
    decode_steps: int = 1
    # Share identical block-aligned prompt prefixes through the
    # allocator's content-hash index; finished requests' blocks stay
    # cached (LRU-evictable) instead of freed. Off by default: caching
    # retains pool blocks after a request finishes, which changes
    # utilization accounting workloads may assert on.
    enable_prefix_caching: bool = False
    kv_dtype: Optional[object] = None   # None = follow the amp policy
    # Donate the cache pool to the jitted steps so XLA updates it in
    # place instead of materializing a second pool + copy per step
    # (double peak HBM and a full-pool write otherwise). Default off:
    # the axon TPU runtime rejects donated buffers at run time (see
    # bench.py's --donate probe history) and older CPU jaxlibs ignore
    # donation with a warning; flip on for runtimes that support it.
    donate_cache: bool = False
    seed: int = 0


@dataclasses.dataclass
class _QueueEntry:
    """A waiting (or preempted-and-requeued) request. ``generated``
    carries tokens already emitted before a preemption so they are
    never resampled — re-admission re-prefills ``prompt +
    generated[:-1]`` and resumes decoding from ``generated[-1]``.
    ``arrival`` is the request's add_request order: it seeds the
    request's PRNG key, so it must survive preemption unchanged (the
    resumed request continues the SAME key sequence at the next token
    index). ``hashes`` memoizes the prefill sequence's block hash chain
    (the sequence is frozen per entry), so a head blocked on pool
    pressure is not re-hashed on every scheduler tick."""

    request: Request
    arrival: int = 0
    generated: List[int] = dataclasses.field(default_factory=list)
    hashes: Optional[List[str]] = None


@dataclasses.dataclass
class _Slot:
    """Host-side state of one batch lane (prefilling or decoding)."""

    entry: _QueueEntry
    admit_seq: int                # monotonic admission order (preemption
                                  # evicts the largest = youngest)
    tokens: List[int]             # tokens whose K/V belong in the cache;
                                  # grows by one per decoded token
    prefill_len: int              # tokens to cache before decoding starts
    prefill_pos: int              # prompt tokens already cached
    context_len: int              # tokens currently valid in the cache
    blocks: List[int]             # owned/shared block ids, sequence order
    block_hashes: List[str]       # chain hashes per full block (lazy tail)
    num_registered: int           # full blocks already in the prefix index
    generated: List[int]
    last_token: int
    started: bool                 # first token known -> decoding

    @property
    def request(self) -> Request:
        return self.entry.request


class InferenceEngine:
    """Drives a :class:`~apex_tpu.models.gpt.GPTLMHeadModel` (or any
    model exposing the same ``kv_cache=`` apply contract) through
    continuous-batching generation.

    Usage::

        engine = InferenceEngine(model, params, EngineConfig(...))
        engine.add_request(Request("a", prompt, max_new_tokens=32))
        outputs = engine.run()          # {"a": [tok, tok, ...]}

    ``add_request`` may be called at any time, including between
    ``step()`` calls while other requests are mid-generation — that is
    the continuous-batching point.
    """

    def __init__(self, model, params, config: EngineConfig):
        cfg = model.cfg
        self.model = model
        self.params = params
        self.config = config
        self._chunk = (config.prefill_chunk if config.prefill_chunk
                       is not None else config.max_prefill_len)
        if self._chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        if self._chunk > config.max_seq_len:
            raise ValueError("prefill_chunk exceeds max_seq_len")
        if config.decode_steps < 1:
            raise ValueError("decode_steps must be >= 1")
        if config.max_seq_len > cfg.max_position_embeddings:
            raise ValueError(
                f"max_seq_len ({config.max_seq_len}) exceeds the model's "
                f"max_position_embeddings ({cfg.max_position_embeddings})")
        self.max_blocks_per_seq = blocks_needed(config.max_seq_len,
                                                config.block_size)
        self.cache = KVCache.create(
            cfg.num_layers, config.num_blocks, config.block_size,
            cfg.num_heads, cfg.hidden_size // cfg.num_heads,
            dtype=config.kv_dtype)
        self.allocator = BlockAllocator(config.num_blocks)
        self.slots: List[Optional[_Slot]] = [None] * config.max_batch
        self.waiting: deque = deque()
        self.finished: Dict[str, List[int]] = {}
        self._key = jax.random.PRNGKey(config.seed)
        self._arrival_count = 0
        self._admit_count = 0
        self._num_prefills = 0
        self._num_prefill_chunks = 0
        self._num_decode_dispatches = 0
        self._num_tokens_decoded = 0
        self._num_preemptions = 0
        self._num_cow_copies = 0
        self._prefix_hit_blocks = 0
        self._prefix_lookup_blocks = 0
        self._prompt_blocks_allocated = 0
        # the in-flight decode dispatch: (device [B, K] tokens, device
        # [B] counts, the lane indices it covers). Fetched — the only
        # host sync of the decode path — at the NEXT tick, after that
        # tick's admission/prefill work is already dispatched.
        self._pending = None
        # dirty-tracked device mirrors of slot-composition state: the
        # decode block table, and the per-lane sampling/EOS/key arrays.
        # Steady-state decode ticks reuse them without a rebuild.
        self._dev_tables = DeviceMirror()
        self._dev_lanes = DeviceMirror()
        self._table_rebuilds = 0
        # the fixed program set; anything else jitted here would break
        # the compile-count contract the tests pin. Arg 1 is the cache
        # pool in every signature (donated when the runtime allows).
        donate = (1,) if config.donate_cache else ()
        self._prefill = jax.jit(self._prefill_impl, donate_argnums=donate)
        self._decode = jax.jit(self._decode_impl, donate_argnums=donate)
        self._cow = jax.jit(
            copy_block, donate_argnums=(0,) if config.donate_cache else ())

    # -- the jitted programs ----------------------------------------------

    def _prefill_impl(self, params, cache, ids, positions, seq_len,
                      write_start, sample_idx, table, key, temp, top_k,
                      top_p):
        logits, cache = self.model.apply(
            params, ids, deterministic=True, kv_cache=cache,
            block_tables=table, cache_positions=positions,
            seq_lens=seq_len, write_start=write_start)
        last = jnp.take_along_axis(
            logits, sample_idx[:, None, None], axis=1)[:, 0]   # [1, V]
        # ``key`` is the REQUEST's key; the first generated token is
        # token index 0 of its per-token key chain (decode continues at
        # index 1), so schedule changes never perturb the draw
        tok = sample_tokens(last, jax.random.fold_in(key, 0),
                            temp, top_k, top_p)
        return cache, tok

    def _decode_impl(self, params, cache, tokens, tables, context_lens,
                     budgets, gen_counts, eos_ids, lane_keys, temp,
                     top_k, top_p):
        """K = ``decode_steps`` fused decode iterations in ONE dispatch.

        Each scan step writes the carried token's K/V at the lane's
        context position, attends through the (loop-invariant) block
        table, samples the next token with the lane's per-token key,
        and feeds it back. Lanes freeze — stop writing, emit ``-1`` —
        once their remaining ``budgets`` hit zero or they sample their
        EOS id (``eos_ids``; ``-1`` = none); a frozen lane's query
        still rides the batch but its ``write_start`` sits one past its
        context position, so the scatter drops. Returns the updated
        cache and ``[B, K]`` emitted tokens — ``-1`` where nothing was
        emitted, so each lane's count is the length of its non-sentinel
        prefix (token ids are always ``>= 0``; the host derives counts
        from the one fetched array instead of a second device output).
        """
        def body(carry, _):
            cache, tok, ctx, budget, gcount = carry
            act = budget > 0
            write_start = jnp.where(act, ctx, ctx + 1)
            logits, cache = self.model.apply(
                params, tok[:, None], deterministic=True, kv_cache=cache,
                block_tables=tables, cache_positions=ctx[:, None],
                seq_lens=ctx + 1, write_start=write_start)
            keys = jax.vmap(jax.random.fold_in)(lane_keys, gcount)
            new = sample_tokens_per_lane(logits[:, 0], keys, temp, top_k,
                                         top_p)
            emitted = act.astype(jnp.int32)
            out = jnp.where(act, new, jnp.int32(-1))
            budget = budget - emitted
            stop = (budget <= 0) | ((eos_ids >= 0) & (new == eos_ids))
            cont = act & ~stop
            # zeroing the budget on EOS folds both stop conditions into
            # the single ``budget > 0`` activity test next iteration
            carry = (cache, jnp.where(cont, new, tok), ctx + emitted,
                     jnp.where(cont, budget, jnp.int32(0)),
                     gcount + emitted)
            return carry, out

        (cache, _, _, _, _), toks = jax.lax.scan(
            body, (cache, tokens, context_lens, budgets, gen_counts),
            None, length=self.config.decode_steps)
        return cache, toks.T

    # -- host-side scheduling ---------------------------------------------

    def add_request(self, request: Request) -> None:
        n = len(request.prompt)
        if n == 0:
            raise ValueError(f"request {request.uid!r}: empty prompt")
        if request.max_new_tokens < 1:
            raise ValueError(
                f"request {request.uid!r}: max_new_tokens must be >= 1 "
                f"(got {request.max_new_tokens}); prefill always samples "
                "the first token")
        if n + request.max_new_tokens > self.config.max_seq_len:
            raise ValueError(
                f"request {request.uid!r}: prompt + max_new_tokens "
                f"({n} + {request.max_new_tokens}) exceeds max_seq_len "
                f"({self.config.max_seq_len})")
        request.sampling.validate()
        self.waiting.append(_QueueEntry(request=request,
                                        arrival=self._arrival_count))
        self._arrival_count += 1

    def _request_key(self, entry: _QueueEntry):
        """The request's own PRNG key: engine seed x arrival order.
        Token ``j`` of the request is drawn with ``fold_in(key, j)`` —
        never from a step counter — so draws are invariant to lane
        placement, batch composition, ``decode_steps``, and
        preemption/resume (the re-queued entry keeps its arrival)."""
        return jax.random.fold_in(self._key, entry.arrival)

    def _invalidate_lanes(self) -> None:
        """Slot composition changed (admit/start/finish/preempt): both
        the decode table and the per-lane arrays must rebuild."""
        self._dev_lanes.invalidate()
        self._dev_tables.invalidate()

    def _invalidate_tables(self) -> None:
        """A lane's block list changed (growth/CoW): same lanes, new
        table rows."""
        self._dev_tables.invalidate()

    def _host_tables(self, decode_only: bool = False) -> np.ndarray:
        """[max_batch, max_blocks_per_seq] host tables (-1 = unmapped).
        ``decode_only`` leaves still-prefilling lanes unmapped so the
        decode step's stray write at position 0 drops out of bounds
        instead of corrupting their first block."""
        t = np.full((self.config.max_batch, self.max_blocks_per_seq), -1,
                    np.int32)
        for i, slot in enumerate(self.slots):
            if slot is None or (decode_only and not slot.started):
                continue
            t[i, : len(slot.blocks)] = slot.blocks
        return t

    def _sampling_arrays(self, per_slot):
        temp = np.zeros(len(per_slot), np.float32)
        top_k = np.zeros(len(per_slot), np.int32)
        top_p = np.ones(len(per_slot), np.float32)
        for i, sp in enumerate(per_slot):
            if sp is not None:
                temp[i], top_k[i], top_p[i] = (sp.temperature, sp.top_k,
                                               sp.top_p)
        return (jnp.asarray(temp), jnp.asarray(top_k), jnp.asarray(top_p))

    def _build_decode_tables(self):
        self._table_rebuilds += 1
        return device_block_table(self._host_tables(decode_only=True),
                                  self.config.num_blocks)

    def _build_lane_meta(self):
        """The slot-composition-keyed decode inputs: sampling knobs,
        EOS ids (-1 = none), and per-request PRNG keys, one row per
        lane (zeros/-1 for lanes that are empty or still prefilling —
        their draws are masked to the sentinel on-device)."""
        B = self.config.max_batch
        temp, top_k, top_p = self._sampling_arrays(
            [s.request.sampling if s is not None and s.started else None
             for s in self.slots])
        eos = np.full(B, -1, np.int32)
        arrivals = np.zeros(B, np.int32)
        for i, s in enumerate(self.slots):
            if s is None or not s.started:
                continue
            if s.request.eos_token_id is not None:
                eos[i] = s.request.eos_token_id
            arrivals[i] = s.entry.arrival
        keys = jax.vmap(lambda a: jax.random.fold_in(self._key, a))(
            jnp.asarray(arrivals))
        return temp, top_k, top_p, jnp.asarray(eos), keys

    def _finish(self, idx: int) -> None:
        """Release the slot: refs drop, and with prefix caching on the
        registered blocks stay cached (evictable) rather than freed.
        Released DEEPEST-first: eviction pops the oldest insertion, and
        evicting a chain's head block orphans every descendant (the
        lookup misses at hash 0), so the tail must age out before the
        head for partial chains to stay matchable."""
        slot = self.slots[idx]
        self.allocator.free(list(reversed(slot.blocks)))
        self.finished[slot.request.uid] = slot.generated
        self.slots[idx] = None
        self._invalidate_lanes()

    def _record_token(self, idx: int, token: int) -> None:
        """Append a sampled token to a slot, finishing on EOS/max-len."""
        slot = self.slots[idx]
        slot.generated.append(token)
        slot.last_token = token
        req = slot.request
        if ((req.eos_token_id is not None and token == req.eos_token_id)
                or len(slot.generated) >= req.max_new_tokens):
            self._finish(idx)

    # -- prefix caching ----------------------------------------------------

    def _seq_hashes(self, tokens: Sequence[int]) -> List[str]:
        bs = self.config.block_size
        hashes, prev = [], None
        for j in range(len(tokens) // bs):
            prev = hash_block_tokens(prev, tokens[j * bs: (j + 1) * bs])
            hashes.append(prev)
        return hashes

    def _register_full_blocks(self, slot: _Slot) -> None:
        """Index every newly-FULL block of the slot (prompt blocks as
        chunks land, generated blocks as decode crosses boundaries)."""
        if not self.config.enable_prefix_caching:
            return
        bs = self.config.block_size
        n_full = slot.context_len // bs
        while slot.num_registered < n_full:
            j = slot.num_registered
            if j >= len(slot.block_hashes):
                prev = slot.block_hashes[j - 1] if j else None
                slot.block_hashes.append(hash_block_tokens(
                    prev, slot.tokens[j * bs: (j + 1) * bs]))
            self.allocator.register_prefix(slot.block_hashes[j],
                                           slot.blocks[j])
            slot.num_registered += 1

    # -- admission (optimistic: current need, not worst case) --------------

    def _admit(self) -> int:
        """Move waiting requests into free lanes while the pool can
        cover their CURRENT need — the uncached prompt-tail blocks plus
        one (vs. the old worst-case reservation of the full generation
        budget, which collapsed pool utilization under long
        ``max_new_tokens``; over-commit is safe now that decode-time
        exhaustion preempts instead of aborting). Prefix caching makes
        the need smaller still: the longest cached block-aligned prefix
        is shared by reference, and only the tail is prefilled."""
        bs = self.config.block_size
        admitted = 0
        for idx in range(self.config.max_batch):
            if not self.waiting or self.slots[idx] is not None:
                continue
            entry = self.waiting[0]
            seq = list(entry.request.prompt)
            if entry.generated:
                seq += entry.generated[:-1]   # resume: re-cache history
            L = len(seq)
            matched: List[int] = []
            hashes: List[str] = []
            if self.config.enable_prefix_caching:
                if entry.hashes is None:
                    entry.hashes = self._seq_hashes(seq)
                hashes = entry.hashes
                matched = self.allocator.lookup_prefix(hashes)
            tail = blocks_needed(L, bs) - len(matched)
            # current need = blocks through the FIRST decode write
            # (position L): blocks_needed(L + 1). That is tail + 1 only
            # when the prompt exactly fills its blocks — an exact-fit
            # request whose whole generation lives in the last partial
            # block needs no headroom at all
            need = blocks_needed(L + 1, bs) - len(matched)
            # matched blocks that are currently cached (refcount 0)
            # stop being evictable once we take them, so they don't
            # count toward the capacity the tail can draw from
            reviving = sum(1 for b in matched
                           if self.allocator.refcount(b) == 0)
            if (need > self.allocator.num_free
                    + self.allocator.num_cached - reviving):
                break   # FIFO: don't let a small request starve the head
            self.allocator.acquire(matched)
            self.waiting.popleft()
            blocks = matched + (self.allocator.alloc(tail) if tail else [])
            m_tok = len(matched) * bs
            self._prefix_lookup_blocks += len(hashes)
            self._prefix_hit_blocks += len(matched)
            self._prompt_blocks_allocated += tail
            self._admit_count += 1
            slot = _Slot(entry=entry, admit_seq=self._admit_count,
                         tokens=seq, prefill_len=L, prefill_pos=m_tok,
                         context_len=m_tok, blocks=blocks,
                         block_hashes=list(hashes),
                         num_registered=len(matched), generated=[],
                         last_token=0, started=False)
            if entry.generated and m_tok == L:
                # resumed and fully cached: nothing to recompute at all
                slot.generated = list(entry.generated)
                slot.last_token = slot.generated[-1]
                slot.started = True
            self.slots[idx] = slot
            self._invalidate_lanes()
            admitted += 1
        return admitted

    # -- chunked prefill ---------------------------------------------------

    def _prefill_tick(self) -> bool:
        """Run ONE ``[1, prefill_chunk]`` piece for the oldest admitted
        request still mid-prompt — at most one chunk per step, ahead of
        the decode dispatch, so long prompts load without stalling the
        streaming slots. A fully-prefix-cached prompt still runs one
        final pass with writes suppressed (``write_start == L``): the
        last position's logits are recomputed from the shared blocks
        without allocating or touching a single one."""
        cand = [(s.admit_seq, i) for i, s in enumerate(self.slots)
                if s is not None and not s.started]
        if not cand:
            return False
        idx = min(cand)[1]
        slot = self.slots[idx]
        L, C = slot.prefill_len, self._chunk
        if slot.prefill_pos < L:
            start = slot.prefill_pos
        else:                       # fully cached: logits-only pass
            start = max(0, L - C)
        end = min(start + C, L)
        ids = np.zeros((1, C), np.int32)
        ids[0, : end - start] = slot.tokens[start:end]
        positions = (start + np.arange(C, dtype=np.int32))[None]
        table = np.full((1, self.max_blocks_per_seq), -1, np.int32)
        table[0, : len(slot.blocks)] = slot.blocks
        temp, top_k, top_p = self._sampling_arrays([slot.request.sampling])
        self.cache, tok = self._prefill(
            self.params, self.cache, jnp.asarray(ids),
            jnp.asarray(positions),
            jnp.asarray([end], jnp.int32),
            jnp.asarray([slot.prefill_pos], jnp.int32),     # write_start
            jnp.asarray([(L - 1) - start], jnp.int32),      # sample_idx
            device_block_table(table, self.config.num_blocks),
            self._request_key(slot.entry), temp, top_k, top_p)
        self._num_prefill_chunks += 1
        slot.prefill_pos = end
        slot.context_len = max(slot.context_len, end)
        self._register_full_blocks(slot)
        if end == L:
            self._num_prefills += 1
            slot.started = True
            self._invalidate_lanes()
            if slot.entry.generated:
                # resumed after preemption: the history's tokens are
                # already emitted — never resample them
                slot.generated = list(slot.entry.generated)
                slot.last_token = slot.generated[-1]
            else:
                self._record_token(idx, int(tok[0]))
        return True

    # -- decode-time block growth, CoW, preemption -------------------------

    def _preempt_for(self, requester: int) -> bool:
        """Free the YOUNGEST lane to un-wedge an allocation for
        ``requester``; its request re-queues at the front carrying its
        generated tokens. Preempting youngest-first guarantees the
        oldest request always progresses, so the system drains. Returns
        False when the requester is the only lane (nothing to free —
        the pool is simply too small for it)."""
        cand = [(s.admit_seq, i) for i, s in enumerate(self.slots)
                if s is not None]
        if len(cand) <= 1:
            return False
        idx = max(cand)[1]
        slot = self.slots[idx]
        gen = (list(slot.generated) if slot.started
               else list(slot.entry.generated))
        # deepest-first, same as _finish: keep evictable chains matchable
        self.allocator.free(list(reversed(slot.blocks)))
        self.waiting.appendleft(_QueueEntry(request=slot.request,
                                            arrival=slot.entry.arrival,
                                            generated=gen))
        self.slots[idx] = None
        self._invalidate_lanes()
        self._num_preemptions += 1
        return True

    def _ensure_decode_blocks(self) -> None:
        """Each started slot is about to write K/V at positions
        ``context_len .. context_len + span - 1`` (``span`` = the
        coming dispatch's emitted-token bound: ``decode_steps`` capped
        by the lane's remaining budget) — make sure PRIVATE blocks
        cover the whole span: allocate the missing tail (preempting the
        youngest lane if the pool is dry), and copy-on-write any
        covering block shared with another sequence (a full-block
        prefix match never shares a partial tail, so CoW is a guard for
        exotic sharing patterns, not the steady state). Reserving the
        span UP FRONT keeps the scan free of host intervention: a
        mid-scan allocation failure is impossible, so preemption
        granularity is K tokens, decided before the dispatch."""
        bs = self.config.block_size
        K = self.config.decode_steps
        order = sorted((s.admit_seq, i) for i, s in enumerate(self.slots)
                       if s is not None and s.started)
        for _, i in order:
            while self.slots[i] is not None:
                slot = self.slots[i]
                span = min(K, slot.request.max_new_tokens
                           - len(slot.generated))
                need = blocks_needed(slot.context_len + span, bs)
                if len(slot.blocks) < need:
                    try:
                        slot.blocks.extend(
                            self.allocator.alloc(need - len(slot.blocks)))
                        self._invalidate_tables()
                    except CacheOutOfBlocks:
                        if not self._preempt_for(i):
                            raise CacheOutOfBlocks(
                                f"request {slot.request.uid!r} cannot grow "
                                f"past {slot.context_len} cached tokens: "
                                f"{self.allocator.num_free} blocks free of "
                                f"{self.allocator.num_blocks} and no other "
                                "lane left to preempt")
                    continue   # re-check: the slot itself may be gone
                first = slot.context_len // bs
                last = (slot.context_len + span - 1) // bs
                j = next((j for j in range(first, last + 1)
                          if self.allocator.refcount(slot.blocks[j]) > 1),
                         None)
                if j is None:
                    break
                try:
                    nb = self.allocator.alloc(1)[0]
                except CacheOutOfBlocks:
                    if not self._preempt_for(i):
                        raise CacheOutOfBlocks(
                            f"request {slot.request.uid!r}: cannot "
                            "copy-on-write a shared block, pool "
                            "exhausted and no lane left to preempt")
                    continue
                b = slot.blocks[j]
                self.cache = self._cow(self.cache,
                                       jnp.int32(b), jnp.int32(nb))
                self.allocator.free([b])
                slot.blocks[j] = nb
                self._invalidate_tables()
                # the copy diverges from the indexed contents the
                # moment we append; registration state stays with
                # the ORIGINAL block
                if slot.num_registered > j:
                    slot.num_registered = j
                self._num_cow_copies += 1
                # loop again: the span may cross FURTHER shared blocks

    # -- the fused decode dispatch + deferred drain ------------------------

    def _dispatch_decode(self, active: List[int]) -> None:
        """Launch the K-step fused decode for ``active`` lanes and
        leave the result in flight (``self._pending``). Only the small
        per-tick arrays (tokens, context lens, budgets, counts) upload
        here; the block table and lane meta come from their mirrors."""
        B = self.config.max_batch
        tokens = np.zeros(B, np.int32)
        ctx = np.zeros(B, np.int32)
        budgets = np.zeros(B, np.int32)
        gcounts = np.zeros(B, np.int32)
        for i in active:
            slot = self.slots[i]
            tokens[i] = slot.last_token
            ctx[i] = slot.context_len
            budgets[i] = (slot.request.max_new_tokens
                          - len(slot.generated))
            gcounts[i] = len(slot.generated)
        tables = self._dev_tables.get(self._build_decode_tables)
        temp, top_k, top_p, eos, keys = self._dev_lanes.get(
            self._build_lane_meta)
        self.cache, toks = self._decode(
            self.params, self.cache, jnp.asarray(tokens), tables,
            jnp.asarray(ctx), jnp.asarray(budgets), jnp.asarray(gcounts),
            eos, keys, temp, top_k, top_p)
        self._num_decode_dispatches += 1
        self._pending = (toks, list(active))

    def _drain_decode(self) -> bool:
        """The deferred host sync: fetch the in-flight dispatch's
        ``[B, K]`` tokens (the ONLY decode-path block on the device)
        and replay them through the per-token bookkeeping —
        cache-token append, block registration, EOS/budget finish. The
        device's stop mask mirrors ``_record_token`` exactly, so a lane
        that froze mid-scan finishes here on the same token."""
        if self._pending is None:
            return False
        toks, active = self._pending
        self._pending = None
        toks = np.asarray(toks)
        # each lane's emitted tokens are its non-sentinel prefix (lanes
        # freeze permanently mid-scan, and real token ids are >= 0)
        counts = (toks >= 0).sum(axis=1)
        for i in active:
            slot = self.slots[i]
            for j in range(int(counts[i])):
                slot.tokens.append(slot.last_token)   # its K/V landed
                slot.context_len += 1
                self._register_full_blocks(slot)
                self._record_token(i, int(toks[i, j]))
                if self.slots[i] is None:
                    break
            self._num_tokens_decoded += int(counts[i])
        return True

    def step(self) -> None:
        """One scheduler tick: admit, run at most one prefill chunk,
        drain the previous tick's in-flight decode, then dispatch one
        fused K-step decode for every started slot (if any). The drain
        comes AFTER admission/prefill on purpose — tick t+1's host
        scheduling work overlaps tick t's device decode (the deferred
        sync) — with an admission top-up behind it so lanes freed by
        the drain don't idle a tick."""
        admitted = self._admit()
        chunked = self._prefill_tick()
        synced = self._drain_decode()
        if synced:
            admitted += self._admit()
        if all(s is None for s in self.slots):
            if self.waiting and not admitted and not chunked and not synced:
                # zero live sequences and nothing in flight means
                # nothing will ever free a block — the queue head can
                # never be admitted (the pool is undersized for it).
                # Raise, don't spin.
                entry = self.waiting[0]
                need = blocks_needed(len(entry.request.prompt) + 1,
                                     self.config.block_size)
                raise CacheOutOfBlocks(
                    f"request {entry.request.uid!r} needs {need} blocks "
                    f"to admit but only {self.allocator.num_blocks} exist "
                    "in the pool")
            return
        active = [i for i, s in enumerate(self.slots)
                  if s is not None and s.started]
        if not active:
            return
        self._ensure_decode_blocks()
        # preemption may have cleared lanes — re-collect
        active = [i for i, s in enumerate(self.slots)
                  if s is not None and s.started]
        if not active:
            return
        self._dispatch_decode(active)

    @property
    def has_work(self) -> bool:
        """True while anything is queued, resident in a lane, or IN
        FLIGHT (an undrained decode dispatch). This is ``run()``'s loop
        condition, public so external step-at-a-time drivers (bench.py
        samples utilization per tick) drain completely without
        duplicating it — a hand-rolled ``waiting or slots`` check would
        silently drop the last dispatch's tokens."""
        return (bool(self.waiting) or self._pending is not None
                or any(s is not None for s in self.slots))

    def run(self) -> Dict[str, List[int]]:
        """Drain: step until every queued, active, and in-flight
        request finishes. Returns ``{uid: generated_token_ids}``."""
        while self.has_work:
            self.step()
        out, self.finished = self.finished, {}
        return out

    def stats(self) -> Dict[str, float]:
        alloc = self.allocator
        lookups = self._prefix_lookup_blocks
        return {
            "prefill_compilations": self._prefill._cache_size(),
            "decode_compilations": self._decode._cache_size(),
            "num_prefills": self._num_prefills,
            "num_prefill_chunks": self._num_prefill_chunks,
            "num_decode_dispatches": self._num_decode_dispatches,
            # tokens actually emitted by decode dispatches (drained
            # ones; an in-flight dispatch counts after its sync). The
            # dispatches:tokens ratio is the multi-step amortization.
            "num_tokens_decoded": self._num_tokens_decoded,
            # back-compat alias: pre-multistep dashboards/tests read
            # num_decode_steps, which meant DISPATCHES (at K=1 the two
            # were indistinguishable)
            "num_decode_steps": self._num_decode_dispatches,
            "decode_table_rebuilds": self._table_rebuilds,
            "num_preemptions": self._num_preemptions,
            "num_cow_copies": self._num_cow_copies,
            "num_cache_evictions": alloc.num_evictions,
            "active_slots": sum(s is not None for s in self.slots),
            "waiting": len(self.waiting),
            "cache_utilization": alloc.utilization,
            "blocks_free": alloc.num_free,
            "blocks_cached": alloc.num_cached,
            "blocks_active": alloc.num_used,
            "prefix_lookup_blocks": lookups,
            "prefix_hit_blocks": self._prefix_hit_blocks,
            "prefix_cache_hit_rate": (self._prefix_hit_blocks / lookups
                                      if lookups else 0.0),
            "prompt_blocks_allocated": self._prompt_blocks_allocated,
        }
